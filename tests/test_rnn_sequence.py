"""RNN stack (fused scan op, LSTM/GRU/SimpleRNN layers, BPTT grads) and
the masked sequence ops.

Parity targets: operators/rnn_op / lstm_op.cc / gru_op.cc,
python/paddle/nn/layer/rnn.py, operators/sequence_ops/. LSTM/GRU
numerics are validated against torch.nn.LSTM/GRU (same gate math and
weight layout), gradients by numerical check through the scan.
"""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.dygraph.tape import run_op
from op_test import OpTest
from paddle_tpu.dygraph.tensor import Tensor


def _np(t):
    return np.asarray(t.value)


def _copy_weights_to_torch(m, tm, num_layers=1, ndir=1):
    import torch
    for layer in range(num_layers):
        for d in range(ndir):
            sfx = f"_l{layer}" + ("_rev" if d else "")
            tsfx = f"_l{layer}" + ("_reverse" if d else "")
            for ours, theirs in (
                    (f"weight_ih{sfx}", f"weight_ih{tsfx}"),
                    (f"weight_hh{sfx}", f"weight_hh{tsfx}"),
                    (f"bias_ih{sfx}", f"bias_ih{tsfx}"),
                    (f"bias_hh{sfx}", f"bias_hh{tsfx}")):
                getattr(tm, theirs).data = torch.from_numpy(
                    _np(getattr(m, ours)).copy())


@pytest.mark.parametrize("cls,tcls", [("LSTM", "LSTM"), ("GRU", "GRU")])
def test_rnn_matches_torch(cls, tcls):
    import torch

    pt.seed(0)
    b, s, din, h = 3, 7, 5, 4
    m = getattr(nn, cls)(din, h)
    tm = getattr(torch.nn, tcls)(din, h, batch_first=True)
    _copy_weights_to_torch(m, tm)

    x = np.random.RandomState(0).randn(b, s, din).astype(np.float32)
    out, state = m(pt.to_tensor(x))
    with torch.no_grad():
        tout, tstate = tm(torch.from_numpy(x))
    np.testing.assert_allclose(_np(out), tout.numpy(), rtol=1e-5,
                               atol=1e-5)
    th = tstate[0] if cls == "LSTM" else tstate
    hs = state[0] if cls == "LSTM" else state
    np.testing.assert_allclose(_np(hs), th.numpy(), rtol=1e-5, atol=1e-5)


def test_bidirectional_multilayer_lstm_matches_torch():
    import torch

    pt.seed(1)
    b, s, din, h = 2, 5, 3, 4
    m = nn.LSTM(din, h, num_layers=2, direction="bidirect")
    tm = torch.nn.LSTM(din, h, num_layers=2, bidirectional=True,
                       batch_first=True)
    _copy_weights_to_torch(m, tm, num_layers=2, ndir=2)
    x = np.random.RandomState(1).randn(b, s, din).astype(np.float32)
    out, (hn, cn) = m(pt.to_tensor(x))
    with torch.no_grad():
        tout, (thn, tcn) = tm(torch.from_numpy(x))
    np.testing.assert_allclose(_np(out), tout.numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(_np(hn), thn.numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(_np(cn), tcn.numpy(), rtol=1e-5,
                               atol=1e-5)


def test_lstm_gradients_match_torch():
    import torch

    pt.seed(2)
    b, s, din, h = 2, 4, 3, 3
    m = nn.LSTM(din, h)
    tm = torch.nn.LSTM(din, h, batch_first=True)
    _copy_weights_to_torch(m, tm)
    x = np.random.RandomState(2).randn(b, s, din).astype(np.float32)

    out, _ = m(pt.to_tensor(x))
    out.sum().backward()

    tx = torch.from_numpy(x)
    tout, _ = tm(tx)
    tout.sum().backward()
    np.testing.assert_allclose(_np(m.weight_ih_l0.grad),
                               tm.weight_ih_l0.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(_np(m.weight_hh_l0.grad),
                               tm.weight_hh_l0.grad.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_variable_lengths_freeze_state():
    pt.seed(3)
    b, s, din, h = 2, 6, 3, 4
    m = nn.LSTM(din, h)
    x = np.random.RandomState(3).randn(b, s, din).astype(np.float32)
    lengths = np.array([6, 3], np.int64)
    out, (hn, _) = m(pt.to_tensor(x), sequence_length=lengths)
    # padded steps output zeros
    np.testing.assert_allclose(_np(out)[1, 3:], 0.0, atol=1e-7)
    # final state of row 1 equals state at t=3 (run truncated input)
    out2, (hn2, _) = m(pt.to_tensor(x[1:2, :3]))
    np.testing.assert_allclose(_np(hn)[0, 1], _np(hn2)[0, 0], rtol=1e-5,
                               atol=1e-6)


def test_cells_single_step():
    pt.seed(4)
    cell = nn.LSTMCell(5, 4)
    x = np.random.RandomState(4).randn(3, 5).astype(np.float32)
    out, (h, c) = cell(pt.to_tensor(x))
    assert _np(out).shape == (3, 4)
    assert _np(h).shape == (1, 3, 4)
    g = nn.GRUCell(5, 4)
    out2, h2 = g(pt.to_tensor(x))
    assert _np(out2).shape == (3, 4)


# ------------------------------------------------------- sequence ops

def _seq_op(op, ins, attrs):
    tin = {k: [Tensor(np.asarray(v)) for v in vs] for k, vs in ins.items()}
    return {k: [_np(t) for t in ts]
            for k, ts in run_op(op, tin, attrs).items()}


def test_sequence_pool_modes():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    lengths = np.array([3, 2], np.int64)
    for ptype, expect in (
            ("SUM", np.stack([x[0].sum(0), x[1, :2].sum(0)])),
            ("AVERAGE", np.stack([x[0].mean(0), x[1, :2].mean(0)])),
            ("MAX", np.stack([x[0].max(0), x[1, :2].max(0)])),
            ("LAST", np.stack([x[0, 2], x[1, 1]])),
            ("FIRST", x[:, 0])):
        out = _seq_op("sequence_pool", {"X": [x], "Length": [lengths]},
                      {"pooltype": ptype})["Out"][0]
        np.testing.assert_allclose(out, expect, err_msg=ptype)


def test_sequence_mask_softmax_reverse():
    lengths = np.array([2, 4], np.int64)
    mask = _seq_op("sequence_mask", {"X": [lengths]},
                   {"maxlen": 5, "out_dtype": "int32"})["Y"][0]
    np.testing.assert_array_equal(
        mask, [[1, 1, 0, 0, 0], [1, 1, 1, 1, 0]])

    x = np.random.RandomState(0).randn(2, 5).astype(np.float32)
    probs = _seq_op("sequence_softmax",
                    {"X": [x], "Length": [lengths]}, {})["Out"][0]
    np.testing.assert_allclose(probs.sum(1), [1.0, 1.0], rtol=1e-6)
    assert (probs[0, 2:] == 0).all()

    xr = _seq_op("sequence_reverse",
                 {"X": [x], "Length": [lengths]}, {})["Out"][0]
    np.testing.assert_allclose(xr[0, :2], x[0, :2][::-1])
    np.testing.assert_allclose(xr[0, 2:], x[0, 2:])
    np.testing.assert_allclose(xr[1, :4], x[1, :4][::-1])


# ------------------------------------------------------- decoding

def test_greedy_and_beam_search_gpt():
    from paddle_tpu.models import gpt2_tiny
    from paddle_tpu.models.generation import (beam_search, greedy_search,
                                              sample)

    pt.seed(11)
    model = gpt2_tiny()
    model.eval()
    ids = np.random.RandomState(0).randint(0, 1024, (2, 8)).astype(np.int32)

    out = greedy_search(model, ids, max_new_tokens=5)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(out[:, :8], ids)

    out_s = sample(model, ids, max_new_tokens=5, top_k=8, seed=3)
    assert out_s.shape == (2, 13)

    seqs, scores = beam_search(model, ids, beam_size=3, max_new_tokens=5)
    assert seqs.shape == (2, 13)
    assert np.isfinite(scores).all()
    # beam-1 equals greedy (same argmax path)
    seqs1, _ = beam_search(model, ids, beam_size=1, max_new_tokens=5)
    np.testing.assert_array_equal(seqs1, out)


def test_beam_search_eos_stops():
    from paddle_tpu.models import gpt2_tiny
    from paddle_tpu.models.generation import greedy_search

    pt.seed(12)
    model = gpt2_tiny()
    model.eval()
    ids = np.zeros((1, 4), np.int32)
    # force eos on the first generated token by picking the argmax as eos
    out = greedy_search(model, ids, max_new_tokens=8)
    eos = int(out[0, 4])
    out2 = greedy_search(model, ids, max_new_tokens=8, eos_token_id=eos)
    assert out2.shape[1] <= out.shape[1]


# ------------------------------------ new sequence ops (pad/unpad/...)

def test_sequence_pad_unpad_roundtrip():
    rng = np.random.RandomState(1)
    lengths = np.array([3, 1, 2], np.int64)
    packed = rng.randn(6, 4).astype(np.float32)  # 3+1+2 rows
    out = _seq_op("sequence_pad",
                  {"X": [packed], "Length": [lengths],
                   "PadValue": [np.float32(0)]},
                  {"padded_length": 4})
    padded = out["Out"][0]
    assert padded.shape == (3, 4, 4)
    np.testing.assert_allclose(padded[0, :3], packed[:3])
    np.testing.assert_allclose(padded[1, :1], packed[3:4])
    np.testing.assert_allclose(padded[2, :2], packed[4:6])
    assert (padded[0, 3:] == 0).all() and (padded[1, 1:] == 0).all()

    back = _seq_op("sequence_unpad",
                   {"X": [padded], "Length": [lengths]}, {})
    unp, total = back["Out"][0], back["Total"][0]
    assert int(total) == 6
    np.testing.assert_allclose(unp[:6], packed)
    assert (unp[6:] == 0).all()


def test_sequence_conv_matches_reference_window():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 5, 3).astype(np.float32)
    lengths = np.array([5, 3], np.int64)
    w = rng.randn(9, 4).astype(np.float32)  # context 3 x d 3
    out = _seq_op("sequence_conv",
                  {"X": [x], "Filter": [w], "Length": [lengths]},
                  {"contextLength": 3, "contextStart": -1})["Out"][0]
    # numpy reference: row 1 has length 3; context rows outside
    # [0, len) are zero
    xm = x.copy()
    xm[1, 3:] = 0
    for b, ln in enumerate(lengths):
        for t in range(ln):
            window = []
            for k in (-1, 0, 1):
                s = t + k
                window.append(xm[b, s] if 0 <= s < ln else
                              np.zeros(3, np.float32))
            expect = np.concatenate(window) @ w
            np.testing.assert_allclose(out[b, t], expect, rtol=1e-5,
                                       atol=1e-5)
    assert (out[1, 3:] == 0).all()


def test_sequence_slice_concat_enumerate_expand_as():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 5, 2).astype(np.float32)
    off = np.array([1, 0], np.int64)
    ln = np.array([2, 3], np.int64)
    sl = _seq_op("sequence_slice",
                 {"X": [x], "Offset": [off], "Length": [ln]}, {})["Out"][0]
    np.testing.assert_allclose(sl[0, :2], x[0, 1:3])
    np.testing.assert_allclose(sl[1, :3], x[1, :3])
    assert (sl[0, 2:] == 0).all()

    x1 = rng.randn(2, 3, 2).astype(np.float32)
    l1 = np.array([2, 3], np.int64)
    x2 = rng.randn(2, 2, 2).astype(np.float32)
    l2 = np.array([1, 2], np.int64)
    cc = _seq_op("sequence_concat",
                 {"X": [x1, x2], "Length": [l1, l2]}, {})
    out, lens = cc["Out"][0], cc["Length"][0]
    np.testing.assert_array_equal(lens, [3, 5])
    np.testing.assert_allclose(out[0, :2], x1[0, :2])
    np.testing.assert_allclose(out[0, 2:3], x2[0, :1])
    assert (out[0, 3:] == 0).all()
    np.testing.assert_allclose(out[1, :3], x1[1])
    np.testing.assert_allclose(out[1, 3:5], x2[1, :2])

    ids = np.array([[1, 2, 3, 4]], np.int64)
    en = _seq_op("sequence_enumerate", {"X": [ids]},
                 {"win_size": 2, "pad_value": 0})["Out"][0]
    np.testing.assert_array_equal(
        en[0], [[1, 2], [2, 3], [3, 4], [4, 0]])

    feat = rng.randn(2, 3).astype(np.float32)
    ex = _seq_op("sequence_expand_as",
                 {"X": [feat], "Length": [np.array([2, 1], np.int64)]},
                 {"maxlen": 3})["Out"][0]
    np.testing.assert_allclose(ex[0, :2], np.stack([feat[0]] * 2))
    assert (ex[0, 2:] == 0).all() and (ex[1, 1:] == 0).all()


def test_sequence_layers_static_graph():
    """layers.sequence_* builders compose in a static program and the
    padding never leaks (fluid layers/sequence_lod.py parity)."""
    import paddle_tpu.layers as L
    from paddle_tpu.framework import (Executor, Program, Scope,
                                      program_guard, unique_name)

    main, startup = Program(), Program()
    main.random_seed = startup.random_seed = 5
    with program_guard(main, startup), unique_name.guard():
        x = L.data("x", [6, 8])           # [b, s, d]
        lens = L.data("lens", [], dtype="int64")
        c = L.sequence_conv(x, num_filters=8, filter_size=3,
                            sequence_length=lens, act="relu")
        probs = L.sequence_softmax(L.reduce_sum(c, dim=-1), lens)
        pooled = L.sequence_pool(c, "average", lens)
        last = L.sequence_last_step(c, lens)
    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(6)
    feed = {"x": rng.randn(3, 6, 8).astype(np.float32),
            "lens": np.array([6, 4, 2], np.int64)}
    p, pl, lst = exe.run(main, feed=feed,
                         fetch_list=[probs.name, pooled.name, last.name],
                         scope=scope)
    np.testing.assert_allclose(np.asarray(p).sum(1), np.ones(3), rtol=1e-5)
    assert np.asarray(p)[2, 2:].max() == 0
    assert np.asarray(pl).shape == (3, 8)
    assert np.asarray(lst).shape == (3, 8)


class TestSequenceConvGrad(OpTest):
    op_type = "sequence_conv"

    def setup(self):
        rng = np.random.RandomState(7)
        self.inputs = {
            "X": [("x", rng.randn(2, 4, 3).astype(np.float64))],
            "Filter": [("w", rng.randn(9, 2).astype(np.float64))],
            "Length": [("ln", np.array([4, 2], np.int64))],
        }
        self.attrs = {"contextLength": 3, "contextStart": -1}
        self.outputs = {"Out": [("out", np.zeros((2, 4, 2)))]}

    def test(self):
        self.setup()
        self.check_grad(["x", "w"], "out", max_relative_error=5e-3)


class TestSequencePadGrad(OpTest):
    op_type = "sequence_pad"

    def setup(self):
        rng = np.random.RandomState(8)
        self.inputs = {
            "X": [("x", rng.randn(5, 3).astype(np.float64))],
            "PadValue": [("pv", np.zeros((), np.float64))],
            "Length": [("ln", np.array([3, 2], np.int64))],
        }
        self.attrs = {"padded_length": 4}
        self.outputs = {"Out": [("out", np.zeros((2, 4, 3)))],
                        "Length": [("lout", np.zeros(2, np.int64))]}

    def test(self):
        self.setup()
        self.check_grad(["x"], "out", max_relative_error=5e-3,
                        no_grad_set=("pv",))


class TestSequenceSliceGrad(OpTest):
    op_type = "sequence_slice"

    def setup(self):
        rng = np.random.RandomState(9)
        self.inputs = {
            "X": [("x", rng.randn(2, 5, 2).astype(np.float64))],
            "Offset": [("off", np.array([1, 0], np.int64))],
            "Length": [("ln", np.array([2, 3], np.int64))],
        }
        self.attrs = {}
        self.outputs = {"Out": [("out", np.zeros((2, 5, 2)))]}

    def test(self):
        self.setup()
        self.check_grad(["x"], "out", max_relative_error=5e-3)


def test_sequence_pad_clamps_overlong_lengths():
    """Rows longer than padded_length truncate AND report the clamped
    length, keeping (Out, Length) self-consistent."""
    packed = np.arange(10, dtype=np.float32).reshape(5, 2)
    lengths = np.array([4, 1], np.int64)
    out = _seq_op("sequence_pad",
                  {"X": [packed], "Length": [lengths],
                   "PadValue": [np.float32(0)]},
                  {"padded_length": 3})
    np.testing.assert_array_equal(out["Length"][0], [3, 1])
    np.testing.assert_allclose(out["Out"][0][0], packed[:3])

"""RNN stack (fused scan op, LSTM/GRU/SimpleRNN layers, BPTT grads) and
the masked sequence ops.

Parity targets: operators/rnn_op / lstm_op.cc / gru_op.cc,
python/paddle/nn/layer/rnn.py, operators/sequence_ops/. LSTM/GRU
numerics are validated against torch.nn.LSTM/GRU (same gate math and
weight layout), gradients by numerical check through the scan.
"""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.dygraph.tape import run_op
from paddle_tpu.dygraph.tensor import Tensor


def _np(t):
    return np.asarray(t.value)


def _copy_weights_to_torch(m, tm, num_layers=1, ndir=1):
    import torch
    for layer in range(num_layers):
        for d in range(ndir):
            sfx = f"_l{layer}" + ("_rev" if d else "")
            tsfx = f"_l{layer}" + ("_reverse" if d else "")
            for ours, theirs in (
                    (f"weight_ih{sfx}", f"weight_ih{tsfx}"),
                    (f"weight_hh{sfx}", f"weight_hh{tsfx}"),
                    (f"bias_ih{sfx}", f"bias_ih{tsfx}"),
                    (f"bias_hh{sfx}", f"bias_hh{tsfx}")):
                getattr(tm, theirs).data = torch.from_numpy(
                    _np(getattr(m, ours)).copy())


@pytest.mark.parametrize("cls,tcls", [("LSTM", "LSTM"), ("GRU", "GRU")])
def test_rnn_matches_torch(cls, tcls):
    import torch

    pt.seed(0)
    b, s, din, h = 3, 7, 5, 4
    m = getattr(nn, cls)(din, h)
    tm = getattr(torch.nn, tcls)(din, h, batch_first=True)
    _copy_weights_to_torch(m, tm)

    x = np.random.RandomState(0).randn(b, s, din).astype(np.float32)
    out, state = m(pt.to_tensor(x))
    with torch.no_grad():
        tout, tstate = tm(torch.from_numpy(x))
    np.testing.assert_allclose(_np(out), tout.numpy(), rtol=1e-5,
                               atol=1e-5)
    th = tstate[0] if cls == "LSTM" else tstate
    hs = state[0] if cls == "LSTM" else state
    np.testing.assert_allclose(_np(hs), th.numpy(), rtol=1e-5, atol=1e-5)


def test_bidirectional_multilayer_lstm_matches_torch():
    import torch

    pt.seed(1)
    b, s, din, h = 2, 5, 3, 4
    m = nn.LSTM(din, h, num_layers=2, direction="bidirect")
    tm = torch.nn.LSTM(din, h, num_layers=2, bidirectional=True,
                       batch_first=True)
    _copy_weights_to_torch(m, tm, num_layers=2, ndir=2)
    x = np.random.RandomState(1).randn(b, s, din).astype(np.float32)
    out, (hn, cn) = m(pt.to_tensor(x))
    with torch.no_grad():
        tout, (thn, tcn) = tm(torch.from_numpy(x))
    np.testing.assert_allclose(_np(out), tout.numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(_np(hn), thn.numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(_np(cn), tcn.numpy(), rtol=1e-5,
                               atol=1e-5)


def test_lstm_gradients_match_torch():
    import torch

    pt.seed(2)
    b, s, din, h = 2, 4, 3, 3
    m = nn.LSTM(din, h)
    tm = torch.nn.LSTM(din, h, batch_first=True)
    _copy_weights_to_torch(m, tm)
    x = np.random.RandomState(2).randn(b, s, din).astype(np.float32)

    out, _ = m(pt.to_tensor(x))
    out.sum().backward()

    tx = torch.from_numpy(x)
    tout, _ = tm(tx)
    tout.sum().backward()
    np.testing.assert_allclose(_np(m.weight_ih_l0.grad),
                               tm.weight_ih_l0.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(_np(m.weight_hh_l0.grad),
                               tm.weight_hh_l0.grad.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_variable_lengths_freeze_state():
    pt.seed(3)
    b, s, din, h = 2, 6, 3, 4
    m = nn.LSTM(din, h)
    x = np.random.RandomState(3).randn(b, s, din).astype(np.float32)
    lengths = np.array([6, 3], np.int64)
    out, (hn, _) = m(pt.to_tensor(x), sequence_length=lengths)
    # padded steps output zeros
    np.testing.assert_allclose(_np(out)[1, 3:], 0.0, atol=1e-7)
    # final state of row 1 equals state at t=3 (run truncated input)
    out2, (hn2, _) = m(pt.to_tensor(x[1:2, :3]))
    np.testing.assert_allclose(_np(hn)[0, 1], _np(hn2)[0, 0], rtol=1e-5,
                               atol=1e-6)


def test_cells_single_step():
    pt.seed(4)
    cell = nn.LSTMCell(5, 4)
    x = np.random.RandomState(4).randn(3, 5).astype(np.float32)
    out, (h, c) = cell(pt.to_tensor(x))
    assert _np(out).shape == (3, 4)
    assert _np(h).shape == (1, 3, 4)
    g = nn.GRUCell(5, 4)
    out2, h2 = g(pt.to_tensor(x))
    assert _np(out2).shape == (3, 4)


# ------------------------------------------------------- sequence ops

def _seq_op(op, ins, attrs):
    tin = {k: [Tensor(np.asarray(v)) for v in vs] for k, vs in ins.items()}
    return {k: [_np(t) for t in ts]
            for k, ts in run_op(op, tin, attrs).items()}


def test_sequence_pool_modes():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    lengths = np.array([3, 2], np.int64)
    for ptype, expect in (
            ("SUM", np.stack([x[0].sum(0), x[1, :2].sum(0)])),
            ("AVERAGE", np.stack([x[0].mean(0), x[1, :2].mean(0)])),
            ("MAX", np.stack([x[0].max(0), x[1, :2].max(0)])),
            ("LAST", np.stack([x[0, 2], x[1, 1]])),
            ("FIRST", x[:, 0])):
        out = _seq_op("sequence_pool", {"X": [x], "Length": [lengths]},
                      {"pooltype": ptype})["Out"][0]
        np.testing.assert_allclose(out, expect, err_msg=ptype)


def test_sequence_mask_softmax_reverse():
    lengths = np.array([2, 4], np.int64)
    mask = _seq_op("sequence_mask", {"X": [lengths]},
                   {"maxlen": 5, "out_dtype": "int32"})["Y"][0]
    np.testing.assert_array_equal(
        mask, [[1, 1, 0, 0, 0], [1, 1, 1, 1, 0]])

    x = np.random.RandomState(0).randn(2, 5).astype(np.float32)
    probs = _seq_op("sequence_softmax",
                    {"X": [x], "Length": [lengths]}, {})["Out"][0]
    np.testing.assert_allclose(probs.sum(1), [1.0, 1.0], rtol=1e-6)
    assert (probs[0, 2:] == 0).all()

    xr = _seq_op("sequence_reverse",
                 {"X": [x], "Length": [lengths]}, {})["Out"][0]
    np.testing.assert_allclose(xr[0, :2], x[0, :2][::-1])
    np.testing.assert_allclose(xr[0, 2:], x[0, 2:])
    np.testing.assert_allclose(xr[1, :4], x[1, :4][::-1])


# ------------------------------------------------------- decoding

def test_greedy_and_beam_search_gpt():
    from paddle_tpu.models import gpt2_tiny
    from paddle_tpu.models.generation import (beam_search, greedy_search,
                                              sample)

    pt.seed(11)
    model = gpt2_tiny()
    model.eval()
    ids = np.random.RandomState(0).randint(0, 1024, (2, 8)).astype(np.int32)

    out = greedy_search(model, ids, max_new_tokens=5)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(out[:, :8], ids)

    out_s = sample(model, ids, max_new_tokens=5, top_k=8, seed=3)
    assert out_s.shape == (2, 13)

    seqs, scores = beam_search(model, ids, beam_size=3, max_new_tokens=5)
    assert seqs.shape == (2, 13)
    assert np.isfinite(scores).all()
    # beam-1 equals greedy (same argmax path)
    seqs1, _ = beam_search(model, ids, beam_size=1, max_new_tokens=5)
    np.testing.assert_array_equal(seqs1, out)


def test_beam_search_eos_stops():
    from paddle_tpu.models import gpt2_tiny
    from paddle_tpu.models.generation import greedy_search

    pt.seed(12)
    model = gpt2_tiny()
    model.eval()
    ids = np.zeros((1, 4), np.int32)
    # force eos on the first generated token by picking the argmax as eos
    out = greedy_search(model, ids, max_new_tokens=8)
    eos = int(out[0, 4])
    out2 = greedy_search(model, ids, max_new_tokens=8, eos_token_id=eos)
    assert out2.shape[1] <= out.shape[1]

"""Subgraph detection + engine delegation (framework/subgraph.py;
reference ir/subgraph_detector.cc + tensorrt_engine_op.h pattern)."""

import json

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import (Executor, Program, Scope,
                                  program_guard, unique_name)
from paddle_tpu.framework.ir import IrGraph, new_pass
from paddle_tpu.framework.subgraph import (SubgraphDetector,
                                           register_delegate_engine)


def _build_mixed_program(seed=3):
    """relu -> relu -> sigmoid(unsupported) -> relu -> relu."""
    main, startup = Program(), Program()
    main.random_seed = startup.random_seed = seed
    with program_guard(main, startup), unique_name.guard():
        x = layers.data("x", [8])
        h = layers.relu(x)
        h = layers.relu(h)
        h = layers.sigmoid(h)
        h = layers.relu(h)
        out = layers.relu(h)
    return main, startup, out


def test_detector_splits_on_unsupported_bridge():
    main, _, _ = _build_mixed_program()
    g = IrGraph(main)
    clusters = SubgraphDetector(
        g, lambda n: n.type == "relu").detect(min_size=2)
    # the sigmoid bridge forces TWO clusters of 2 relus each
    assert len(clusters) == 2
    assert all(len(c) == 2 for c in clusters)
    assert all(n.type == "relu" for c in clusters for n in c)


def test_detector_cycle_demotion():
    """A supported pair whose only connection runs through an
    unsupported op must NOT merge (contraction would create a cycle)."""
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        x = layers.data("x", [4])
        a = layers.relu(x)             # supported
        b = layers.sigmoid(a)          # unsupported bridge
        c = layers.relu(b)             # supported
        layers.relu(c)                 # supported, adjacent to c
    g = IrGraph(main)
    clusters = SubgraphDetector(
        g, lambda n: n.type == "relu").detect(min_size=2)
    for cl in clusters:
        idxs = [n.idx for n in cl]
        assert 0 not in idxs or 2 not in idxs, \
            "cluster spans the unsupported bridge"


def test_delegate_pass_outputs_match_original():
    feed = {"x": np.random.RandomState(0).randn(2, 8).astype(np.float32)}

    main, startup, out = _build_mixed_program()
    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    ref = exe.run(main, feed=feed, fetch_list=[out.name], scope=scope)[0]

    p = new_pass("subgraph_delegate_pass",
                 is_supported={"relu"}, min_subgraph_size=2)
    fused = p.apply(IrGraph(main)).to_program()
    types = [op.type for op in fused.global_block().ops]
    assert types.count("subgraph_delegate") == 2
    assert "relu" not in types

    scope2, exe2 = Scope(), Executor()
    exe2.run(startup, scope=scope2)
    got = exe2.run(fused, feed=feed, fetch_list=[out.name],
                   scope=scope2)[0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6)


def test_delegate_carries_parameters_across_boundary():
    """fc params are cluster-external inputs: the delegate must read
    them from the scope like any var (engine-op weights contract)."""
    main, startup = Program(), Program()
    main.random_seed = startup.random_seed = 7
    with program_guard(main, startup), unique_name.guard():
        x = layers.data("x", [6])
        h = layers.fc(x, 5, act=None)
        out = layers.relu(h)
    feed = {"x": np.random.RandomState(1).randn(3, 6).astype(np.float32)}
    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    ref = exe.run(main, feed=feed, fetch_list=[out.name], scope=scope)[0]

    p = new_pass("subgraph_delegate_pass",
                 is_supported={"mul", "elementwise_add", "relu"},
                 min_subgraph_size=2)
    fused = p.apply(IrGraph(main)).to_program()
    assert [op.type for op in fused.global_block().ops].count(
        "subgraph_delegate") == 1
    got = exe.run(fused, feed=feed, fetch_list=[out.name], scope=scope)[0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6)


def test_custom_engine_runner_invoked():
    calls = {}

    def engine(sub_ops, env, ctx):
        calls["n_ops"] = len(sub_ops)
        import jax.numpy as jnp
        v = env[sub_ops[0]["inputs"]["X"][0]]
        for _ in sub_ops:
            v = jnp.maximum(v, 0)
        # single external output contract for this test
        return {sub_ops[-1]["outputs"]["Out"][0]: v}

    register_delegate_engine("test_engine", engine)
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        x = layers.data("x", [4])
        h = layers.relu(x)
        out = layers.relu(h)
    p = new_pass("subgraph_delegate_pass", is_supported={"relu"},
                 min_subgraph_size=2, engine="test_engine")
    fused = p.apply(IrGraph(main)).to_program()
    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    feed = {"x": np.array([[-1.0, 2.0, -3.0, 4.0]], np.float32)}
    got = exe.run(fused, feed=feed, fetch_list=[out.name], scope=scope)[0]
    np.testing.assert_allclose(np.asarray(got),
                               [[0.0, 2.0, 0.0, 4.0]])
    assert calls["n_ops"] == 2


def test_unregistered_engine_raises():
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        x = layers.data("x", [4])
        h = layers.relu(x)
        out = layers.relu(h)
    p = new_pass("subgraph_delegate_pass", is_supported={"relu"},
                 min_subgraph_size=2, engine="missing_engine")
    fused = p.apply(IrGraph(main)).to_program()
    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    with pytest.raises(Exception, match="missing_engine"):
        exe.run(fused, feed={"x": np.zeros((1, 4), np.float32)},
                fetch_list=[out.name], scope=scope)

"""Native (C++) PS server: wire parity with the Python server.

The C++ server (native/ps_server.cpp) must be indistinguishable from
rpc.PSServer through PSClient. Parity: grpc_server.cc transport,
large_scale_kv.h sharded tables, heart_beat_monitor.cc liveness.
"""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.ps.native_server import (NativePSServer,
                                                     make_server)
from paddle_tpu.distributed.ps.rpc import PSClient


@pytest.fixture
def native_servers():
    servers = [NativePSServer("127.0.0.1:0", i, 2) for i in range(2)]
    eps = [f"127.0.0.1:{s.port}" for s in servers]
    yield eps
    for s in servers:
        s.stop()


def test_create_pull_push_sgd_training(native_servers):
    client = PSClient(native_servers)
    client.create_table("emb", 4, optimizer="sgd", lr=1.0, init="zeros")
    ids = np.arange(10, dtype=np.int64)
    rows = client.pull("emb", ids)
    np.testing.assert_allclose(rows, 0.0)
    grads = np.full((10, 4), 0.5, np.float32)
    client.push("emb", ids, grads)
    np.testing.assert_allclose(client.pull("emb", ids), -0.5)
    # duplicate ids combine before the update (scatter-add)
    dup = np.array([0, 0, 1], np.int64)
    client.push("emb", dup, np.ones((3, 4), np.float32))
    got = client.pull("emb", np.array([0, 1], np.int64))
    np.testing.assert_allclose(got[0], -0.5 - 2.0)
    np.testing.assert_allclose(got[1], -0.5 - 1.0)
    assert client.size("emb") == 10
    client.close()


def test_random_init_and_adagrad(native_servers):
    client = PSClient(native_servers)
    client.create_table("ada", 8, optimizer="adagrad", lr=0.1)
    ids = np.arange(6, dtype=np.int64)
    r1 = client.pull("ada", ids)
    assert np.abs(r1).max() > 0  # random init, not zeros
    np.testing.assert_allclose(client.pull("ada", ids), r1)  # stable
    g = np.ones((6, 8), np.float32)
    client.push("ada", ids, g)
    r2 = client.pull("ada", ids)
    # adagrad first step: -lr * g / (sqrt(g^2) + eps) ~= -0.1
    np.testing.assert_allclose(r2 - r1, -0.1, atol=1e-3)
    client.close()


def test_state_save_load_roundtrip(native_servers):
    client = PSClient(native_servers)
    client.create_table("ck", 3, lr=1.0, init="zeros")
    ids = np.arange(7, dtype=np.int64)
    client.push("ck", ids, np.ones((7, 3), np.float32))  # no-op (unpulled)
    client.pull("ck", ids)
    client.push("ck", ids, np.ones((7, 3), np.float32))
    state = client.state("ck")
    assert len(state) == 7
    # wipe by loading into a fresh table on the same servers
    client.create_table("ck2", 3, lr=1.0, init="zeros")
    client.load("ck2", state)
    np.testing.assert_allclose(client.pull("ck2", ids),
                               client.pull("ck", ids))
    client.close()


def test_barrier_and_heartbeat(native_servers):
    client = PSClient(native_servers)
    results = []

    def waiter():
        c2 = PSClient(native_servers)
        results.append(c2.barrier(expected=2, server=0))
        c2.close()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    assert client.barrier(expected=2, server=0)
    t.join(10)
    assert results == [True]

    client.heartbeat(worker_id=3)
    st = client.worker_status(server=0)
    assert st["3"]["alive"]
    dead = client.worker_status(server=0, timeout=1e-9)
    assert not dead["3"]["alive"]
    client.close()


def test_error_keeps_connection(native_servers):
    client = PSClient(native_servers)
    with pytest.raises(RuntimeError, match="not created"):
        client.pull("ghost", np.array([1], np.int64))
    client.create_table("ok", 2, init="zeros")
    assert client.pull("ok", np.array([0], np.int64)).shape == (1, 2)
    client.shutdown_servers()


def test_shutdown_stops_native_server():
    srv = NativePSServer("127.0.0.1:0", 0, 1)
    eps = [f"127.0.0.1:{srv.port}"]
    client = PSClient(eps)
    client.create_table("t", 2)
    client.shutdown_servers()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and srv._lib.ps_running(
            srv._handle or 0):
        time.sleep(0.05)
    # run() returns promptly after a client shutdown
    srv.run()
    srv.stop()


def test_make_server_prefers_native_falls_back():
    s = make_server("127.0.0.1:0", 0, 1)
    assert isinstance(s, NativePSServer)
    s.stop()


def test_parity_python_vs_native_training():
    """Same deterministic workload on both backends -> identical
    tables (zeros init removes RNG differences)."""
    from paddle_tpu.distributed.ps.rpc import PSServer
    py = PSServer("127.0.0.1:0", 0, 1).start()
    py_ep = f"127.0.0.1:{py._tcp.server_address[1]}"
    nat = NativePSServer("127.0.0.1:0", 0, 1)
    nat_ep = f"127.0.0.1:{nat.port}"

    rng = np.random.RandomState(0)
    ids_seq = [rng.randint(0, 50, 32).astype(np.int64) for _ in range(5)]
    grads_seq = [rng.randn(32, 4).astype(np.float32) for _ in range(5)]
    outs = []
    for ep in (py_ep, nat_ep):
        c = PSClient([ep])
        c.create_table("w", 4, optimizer="adagrad", lr=0.05,
                       init="zeros")
        for ids, g in zip(ids_seq, grads_seq):
            c.pull("w", ids)
            c.push("w", ids, g)
        outs.append(c.pull("w", np.arange(50, dtype=np.int64)))
        c.close()
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)
    py.stop()
    nat.stop()


def test_adagrad_accumulators_survive_checkpoint(native_servers):
    """state()/load() carry optimizer accumulators: the restored table
    keeps its decayed step size instead of jumping back to ~lr."""
    client = PSClient(native_servers)
    client.create_table("opt", 2, optimizer="adagrad", lr=0.1,
                        init="zeros")
    ids = np.arange(4, dtype=np.int64)
    client.pull("opt", ids)
    for _ in range(5):
        client.push("opt", ids, np.ones((4, 2), np.float32))
    snap = client.state("opt")
    assert any(k.startswith("a:") for k in snap)
    before = client.pull("opt", ids)

    client.create_table("opt_restored", 2, optimizer="adagrad", lr=0.1,
                        init="zeros")
    client.load("opt_restored", snap)
    # one more identical push on both: updates must match exactly
    client.push("opt", ids, np.ones((4, 2), np.float32))
    client.push("opt_restored", ids, np.ones((4, 2), np.float32))
    np.testing.assert_allclose(client.pull("opt_restored", ids),
                               client.pull("opt", ids), rtol=1e-6)
    # and the step was the DECAYED size, far below lr
    step = np.abs(np.asarray(client.pull("opt", ids)) - before).max()
    assert step < 0.05  # lr/sqrt(6) ~ 0.04, vs fresh-accum 0.1
    client.close()

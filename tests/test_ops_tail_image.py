"""OpTests for the round-4 image + indexing op tail (image_ops.py,
index_ops.py). References from torch where it implements the same
contract; hand-rolled numpy otherwise."""

import numpy as np
import pytest

from op_test import OpTest

RNG = np.random.RandomState(13)


class TestInterp1D3D(OpTest):
    def test_linear_interp(self):
        import torch
        self.op_type = "linear_interp_v2"
        x = RNG.randn(2, 3, 8).astype(np.float64)
        ref = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=16, mode="linear",
            align_corners=False).numpy()
        self.inputs = {"X": x}
        self.outputs = {"Out": ref}
        self.attrs = {"out_w": 16}
        self.check_output(atol=2e-2, rtol=2e-2)

    # the grad checks below finite-difference 5-D/im2col/pooling ops
    # under x64+highest precision — tens of seconds each on one CPU;
    # `slow` keeps the capped tier-1 run inside its budget while ci.sh
    # step 4 (full suite, no marker filter) still runs them
    @pytest.mark.slow
    def test_trilinear_interp(self):
        self.op_type = "trilinear_interp_v2"
        # exactness check: resizing a constant field is identity
        x = np.full((1, 2, 3, 4, 5), 2.5)
        self.inputs = {"X": x}
        self.outputs = {"Out": np.full((1, 2, 6, 8, 10), 2.5)}
        self.attrs = {"out_d": 6, "out_h": 8, "out_w": 10}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")


class TestGridSampler(OpTest):
    op_type = "grid_sampler"

    def _run(self, align, mode, pad, torch_pad):
        import torch
        x = RNG.randn(2, 3, 5, 6).astype(np.float64)
        grid = RNG.uniform(-1.3, 1.3, (2, 4, 4, 2)).astype(np.float64)
        ref = torch.nn.functional.grid_sample(
            torch.from_numpy(x), torch.from_numpy(grid), mode=mode,
            padding_mode=torch_pad, align_corners=align).numpy()
        self.inputs = {"X": x, "Grid": grid}
        self.outputs = {"Output": ref}
        self.attrs = {"align_corners": align, "mode": mode,
                      "padding_mode": pad}
        self.check_output()

    def test_bilinear_zeros(self):
        self._run(True, "bilinear", "zeros", "zeros")

    def test_bilinear_border_noalign(self):
        self._run(False, "bilinear", "border", "border")

    @pytest.mark.slow
    def test_grad(self):
        x = RNG.randn(1, 2, 4, 4).astype(np.float64)
        grid = RNG.uniform(-0.9, 0.9, (1, 3, 3, 2)).astype(np.float64)
        import torch
        tx = torch.from_numpy(x)
        tg = torch.from_numpy(grid)
        ref = torch.nn.functional.grid_sample(
            tx, tg, align_corners=True).numpy()
        self.inputs = {"X": x, "Grid": grid}
        self.outputs = {"Output": ref}
        self.attrs = {"align_corners": True}
        self.check_grad(["X_0"], "Output_0")


class TestAffineGrid(OpTest):
    op_type = "affine_grid"

    def test(self):
        import torch
        theta = RNG.randn(2, 2, 3).astype(np.float64)
        ref = torch.nn.functional.affine_grid(
            torch.from_numpy(theta), (2, 3, 4, 5),
            align_corners=True).numpy()
        self.inputs = {"Theta": theta}
        self.outputs = {"Output": ref}
        self.attrs = {"output_shape": [2, 3, 4, 5], "align_corners": True}
        self.check_output()
        self.check_grad(["Theta_0"], "Output_0")


@pytest.mark.slow
class TestAffineChannel(OpTest):
    op_type = "affine_channel"

    def test(self):
        x = RNG.randn(2, 3, 4, 4)
        s = RNG.rand(3) + 0.5
        b = RNG.randn(3)
        exp = x * s.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1)
        self.inputs = {"X": x, "Scale": s, "Bias": b}
        self.outputs = {"Out": exp}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")


@pytest.mark.slow
class TestPixelShuffle(OpTest):
    op_type = "pixel_shuffle"

    def test(self):
        import torch
        x = RNG.randn(2, 8, 3, 3)
        ref = torch.nn.functional.pixel_shuffle(
            torch.from_numpy(x), 2).numpy()
        self.inputs = {"X": x}
        self.outputs = {"Out": ref}
        self.attrs = {"upscale_factor": 2}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")


class TestSpaceToDepthShuffle(OpTest):
    def test_space_to_depth(self):
        self.op_type = "space_to_depth"
        x = np.arange(2 * 2 * 4 * 4, dtype=np.float64).reshape(2, 2, 4, 4)
        b = 2
        n, c, h, w = x.shape
        v = x.reshape(n, c, h // b, b, w // b, b)
        exp = v.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * 4, 2, 2)
        self.inputs = {"X": x}
        self.outputs = {"Out": exp}
        self.attrs = {"blocksize": 2}
        self.check_output()

    @pytest.mark.slow
    def test_shuffle_channel(self):
        self.op_type = "shuffle_channel"
        x = RNG.randn(2, 6, 3, 3)
        exp = x.reshape(2, 2, 3, 3, 3).transpose(0, 2, 1, 3, 4).reshape(
            2, 6, 3, 3)
        self.inputs = {"X": x}
        self.outputs = {"Out": exp}
        self.attrs = {"group": 2}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")


@pytest.mark.slow
class TestTemporalShift(OpTest):
    op_type = "temporal_shift"

    def test(self):
        n, t, c, h, w = 2, 3, 4, 2, 2
        x = RNG.randn(n * t, c, h, w)
        v = x.reshape(n, t, c, h, w)
        exp = np.zeros_like(v)
        c1 = int(c * 0.25)
        c2 = int(c * 0.5)
        exp[:, :-1, :c1] = v[:, 1:, :c1]
        exp[:, 1:, c1:c2] = v[:, :-1, c1:c2]
        exp[:, :, c2:] = v[:, :, c2:]
        self.inputs = {"X": x}
        self.outputs = {"Out": exp.reshape(n * t, c, h, w)}
        self.attrs = {"seg_num": t, "shift_ratio": 0.25}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")


@pytest.mark.slow
class TestLrn(OpTest):
    op_type = "lrn"

    def test(self):
        x = RNG.randn(2, 6, 3, 3)
        n_, k, alpha, beta = 5, 2.0, 1e-4, 0.75
        sq = x * x
        mid = np.full_like(x, k)
        half = n_ // 2
        for c in range(6):
            lo = max(0, c - half)
            hi = min(6, c + n_ - half)
            mid[:, c] += alpha * sq[:, lo:hi].sum(axis=1)
        exp = x * np.power(mid, -beta)
        self.inputs = {"X": x}
        self.outputs = {"Out": exp, "MidOut": mid}
        self.attrs = {"n": n_, "k": k, "alpha": alpha, "beta": beta}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")


class TestCropPad(OpTest):
    def test_crop_tensor(self):
        self.op_type = "crop_tensor"
        x = RNG.randn(4, 5)
        self.inputs = {"X": x}
        self.outputs = {"Out": x[1:3, 2:5]}
        self.attrs = {"offsets": [1, 2], "shape": [2, 3]}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")

    def test_crop_v1_minus1(self):
        self.op_type = "crop"
        x = RNG.randn(4, 5)
        self.inputs = {"X": x}
        self.outputs = {"Out": x[1:, 2:]}
        self.attrs = {"offsets": [1, 2], "shape": [-1, -1]}
        self.check_output()

    def test_pad_constant_like(self):
        self.op_type = "pad_constant_like"
        x = np.zeros((4, 5))
        y = RNG.randn(2, 3)
        exp = np.full((4, 5), 1.5)
        exp[:2, :3] = y
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": exp}
        self.attrs = {"pad_value": 1.5}
        self.check_output()
        self.check_grad(["Y_0"], "Out_0")


@pytest.mark.slow
class TestUnfold(OpTest):
    op_type = "unfold"

    def test(self):
        import torch
        x = RNG.randn(2, 3, 6, 5)
        ref = torch.nn.functional.unfold(
            torch.from_numpy(x), (3, 2), dilation=1, padding=1,
            stride=2).numpy()
        self.inputs = {"X": x}
        self.outputs = {"Y": ref}
        self.attrs = {"kernel_sizes": [3, 2], "strides": [2, 2],
                      "paddings": [1, 1], "dilations": [1, 1]}
        self.check_output()
        self.check_grad(["X_0"], "Y_0")


class TestMaxPoolWithIndexUnpool(OpTest):
    @pytest.mark.slow
    def test_pool2d_with_index(self):
        import torch
        self.op_type = "max_pool2d_with_index"
        x = RNG.randn(2, 3, 6, 6)
        out_t, idx_t = torch.nn.functional.max_pool2d(
            torch.from_numpy(x), 2, stride=2, return_indices=True)
        self.inputs = {"X": x}
        self.outputs = {"Out": out_t.numpy(),
                        "Mask": idx_t.numpy().astype(np.int32)}
        self.attrs = {"ksize": [2, 2], "strides": [2, 2]}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")

    def test_unpool_roundtrip(self):
        import torch
        self.op_type = "unpool"
        x = RNG.randn(2, 3, 6, 6)
        out_t, idx_t = torch.nn.functional.max_pool2d(
            torch.from_numpy(x), 2, stride=2, return_indices=True)
        ref = torch.nn.functional.max_unpool2d(
            out_t, idx_t, 2, stride=2).numpy()
        self.inputs = {"X": out_t.numpy(),
                       "Indices": idx_t.numpy().astype(np.int32)}
        self.outputs = {"Out": ref}
        self.attrs = {"ksize": [2, 2], "strides": [2, 2],
                      "unpooling_type": "max"}
        self.check_output()

    def test_pool3d_with_index(self):
        import torch
        self.op_type = "max_pool3d_with_index"
        x = RNG.randn(1, 2, 4, 4, 4)
        out_t, idx_t = torch.nn.functional.max_pool3d(
            torch.from_numpy(x), 2, stride=2, return_indices=True)
        self.inputs = {"X": x}
        self.outputs = {"Out": out_t.numpy(),
                        "Mask": idx_t.numpy().astype(np.int32)}
        self.attrs = {"ksize": [2, 2, 2], "strides": [2, 2, 2]}
        self.check_output()


# --------------------------------------------------------------- indexing


class TestIndexSample(OpTest):
    op_type = "index_sample"

    def test(self):
        x = RNG.randn(4, 6)
        idx = RNG.randint(0, 6, (4, 3)).astype(np.int64)
        exp = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": exp}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")


class TestMultiplex(OpTest):
    op_type = "multiplex"

    def test(self):
        a, b, c = RNG.randn(4, 3), RNG.randn(4, 3), RNG.randn(4, 3)
        ids = np.array([[2], [0], [1], [0]], np.int32)
        exp = np.stack([[a, b, c][ids[i, 0]][i] for i in range(4)])
        self.inputs = {"X": [("ma", a), ("mb", b), ("mc", c)],
                       "Ids": ids}
        self.outputs = {"Out": exp}
        self.check_output()
        self.check_grad(["ma", "mb"], "Out_0")


class TestReverse(OpTest):
    op_type = "reverse"

    def test(self):
        x = RNG.randn(3, 4)
        self.inputs = {"X": x}
        self.outputs = {"Out": x[::-1, ::-1].copy()}
        self.attrs = {"axis": [0, 1]}
        self.check_output()


class TestScatterNdAdd(OpTest):
    op_type = "scatter_nd_add"

    def test(self):
        x = RNG.randn(4, 5)
        idx = np.array([[1], [2], [1]], np.int64)
        upd = RNG.randn(3, 5)
        exp = x.copy()
        for i, r in enumerate(idx[:, 0]):
            exp[r] += upd[i]
        self.inputs = {"X": x, "Index": idx, "Updates": upd}
        self.outputs = {"Out": exp}
        self.check_output()
        self.check_grad(["X_0", "Updates_0"], "Out_0")


class TestGatherTree(OpTest):
    op_type = "gather_tree"

    def test(self):
        ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]],
                        [[0, 1], [9, 0]]], np.int64)
        parents = np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                            [[0, 0], [0, 1]]], np.int64)
        # expected via reference backtrace semantics
        t, b, w = ids.shape
        exp = np.zeros_like(ids)
        for bb in range(b):
            for ww in range(w):
                par = ww
                for tt in range(t - 1, -1, -1):
                    exp[tt, bb, ww] = ids[tt, bb, par]
                    par = parents[tt, bb, par]
        self.inputs = {"Ids": ids, "Parents": parents}
        self.outputs = {"Out": exp}
        self.check_output()


class TestSeluMish(OpTest):
    def test_selu(self):
        import torch
        self.op_type = "selu"
        x = RNG.randn(3, 4)
        self.inputs = {"X": x}
        self.outputs = {"Out": torch.nn.functional.selu(
            torch.from_numpy(x)).numpy()}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")

    def test_mish(self):
        import torch
        self.op_type = "mish"
        x = RNG.randn(3, 4)
        self.inputs = {"X": x}
        self.outputs = {"Out": torch.nn.functional.mish(
            torch.from_numpy(x)).numpy()}
        self.attrs = {"threshold": 20.0}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")


class TestConvShift(OpTest):
    op_type = "conv_shift"

    def test(self):
        b, m, n = 2, 7, 3
        x = RNG.randn(b, m)
        y = RNG.randn(b, n)
        exp = np.zeros((b, m))
        for i in range(b):
            for j in range(m):
                for k in range(n):
                    exp[i, j] += x[i, (j + k - n // 2) % m] * y[i, k]
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": exp}
        self.check_output()
        self.check_grad(["X_0", "Y_0"], "Out_0")


class TestRowConv(OpTest):
    op_type = "row_conv"

    def test(self):
        b, t, d, ctx_len = 2, 5, 3, 2
        x = RNG.randn(b, t, d)
        f = RNG.randn(ctx_len, d)
        exp = np.zeros_like(x)
        for c in range(ctx_len):
            xs = np.zeros_like(x)
            xs[:, :t - c if c else t] = x[:, c:]
            exp += xs * f[c]
        self.inputs = {"X": x, "Filter": f}
        self.outputs = {"Out": exp}
        self.check_output()
        self.check_grad(["X_0", "Filter_0"], "Out_0")


class TestPartialOps(OpTest):
    def test_partial_concat(self):
        self.op_type = "partial_concat"
        a, b = RNG.randn(3, 6), RNG.randn(3, 6)
        self.inputs = {"X": [("pa", a), ("pb", b)]}
        self.outputs = {"Out": np.concatenate([a[:, 1:4], b[:, 1:4]], 1)}
        self.attrs = {"start_index": 1, "length": 3}
        self.check_output()

    def test_partial_sum(self):
        self.op_type = "partial_sum"
        a, b = RNG.randn(3, 6), RNG.randn(3, 6)
        self.inputs = {"X": [("pa", a), ("pb", b)]}
        self.outputs = {"Out": a[:, 1:4] + b[:, 1:4]}
        self.attrs = {"start_index": 1, "length": 3}
        self.check_output()
        self.check_grad(["pa", "pb"], "Out_0")


class TestV1Aliases(OpTest):
    def test_expand(self):
        self.op_type = "expand"
        x = RNG.randn(2, 3)
        self.inputs = {"X": x}
        self.outputs = {"Out": np.tile(x, (2, 2))}
        self.attrs = {"expand_times": [2, 2]}
        self.check_output()
        self.check_grad(["X_0"], "Out_0")

    def test_flatten(self):
        self.op_type = "flatten"
        x = RNG.randn(2, 3, 4)
        self.inputs = {"X": x}
        self.outputs = {"Out": x.reshape(2, 12)}
        self.attrs = {"axis": 1}
        self.check_output()

    def test_squeeze_unsqueeze(self):
        self.op_type = "squeeze"
        x = RNG.randn(2, 1, 3)
        self.inputs = {"X": x}
        self.outputs = {"Out": x.reshape(2, 3)}
        self.attrs = {"axes": [1]}
        self.check_output()
        self.op_type = "unsqueeze"
        self.inputs = {"X": x.reshape(2, 3)}
        self.outputs = {"Out": x.reshape(2, 1, 3)}
        self.attrs = {"axes": [1]}
        self.check_output()


class TestMaskedSelect:
    def test_eager(self):
        from paddle_tpu.ops import registry
        ctx = registry.LoweringContext(eager=True)
        out = registry.execute(
            ctx, "masked_select",
            {"X": [np.array([[1.0, 2.0], [3.0, 4.0]])],
             "Mask": [np.array([[True, False], [False, True]])]}, {})
        np.testing.assert_allclose(np.asarray(out["Y"][0]), [1.0, 4.0])

    def test_static_raises(self):
        import paddle_tpu as paddle
        from paddle_tpu.framework import (Executor, Program, Scope,
                                          program_guard)
        prog = Program()
        with program_guard(prog):
            blk = prog.global_block()
            blk.create_var("mx", shape=(2, 2), dtype="float64",
                           is_data=True)
            blk.create_var("mm", shape=(2, 2), dtype="bool", is_data=True)
            blk.create_var("mout")
            blk.append_op("masked_select", {"X": "mx", "Mask": "mm"},
                          {"Y": "mout"}, {})
        exe = Executor()
        with pytest.raises(Exception, match="masked_select|data-dependent"):
            exe.run(prog, feed={"mx": np.ones((2, 2)),
                                "mm": np.ones((2, 2), bool)},
                    fetch_list=["mout"], scope=Scope())

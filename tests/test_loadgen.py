"""Load generator + graceful degradation: the "real traffic" contract.

Three guarantees under test:

- **replayability**: the same seed produces a byte-identical arrival
  trace (all three arrival processes) AND — on a virtual clock with
  pinned predictor costs — identical admit/shed decisions across two
  independent engine runs;
- **elasticity**: the router's AutoscalePolicy grows replicas under
  queue pressure and retires them (drained, never shedding in-flight
  work) when the load passes;
- **chaos crossover**: with fault injection live, goodput degrades
  but the run stays graceful — zero unhandled exceptions, zero leaked
  KV blocks, every lost request accounted for in a shed counter.

Plus the static side: ``predict_serving_compiles`` treats every
admission parameter as a validated no-op, which *is* the
zero-new-compiles contract in regression-test form.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.analysis import predict_serving_compiles
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.resilience import fault_scope
from paddle_tpu.serving import AutoscalePolicy, ReplicaRouter, ServingEngine
from tools.loadgen import Arrival, LoadGen, VirtualClock, warmup


@pytest.fixture(scope="module")
def model():
    pt.seed(13)
    cfg = GPTConfig(vocab_size=97, max_position_embeddings=64,
                    hidden_size=32, num_layers=2, num_heads=4,
                    ffn_hidden_size=64)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


_LG_KW = dict(rate=30.0, duration=0.6, vocab_size=97,
              prompt_tokens=(3, 9), new_tokens=(2, 5),
              priority_mix={0: 0.2, 1: 0.6, 2: 0.2})


def _engine(model, clock, **kw):
    base = dict(max_slots=2, max_len=32, buckets=[8, 16], max_queue=4,
                slo_ttft_ms=60.0, slo_prefill_ms=4.0, slo_tpot_ms=1.5,
                clock=clock)
    base.update(kw)
    return ServingEngine(model, **base)


# --------------------------------------------------------- replayability
@pytest.mark.parametrize("mode", list(LoadGen.MODES))
def test_same_seed_same_trace_bytes(mode):
    a = LoadGen(mode=mode, seed=42, **_LG_KW)
    b = LoadGen(mode=mode, seed=42, **_LG_KW)
    assert a.trace_bytes() == b.trace_bytes()
    assert len(a.schedule()) > 0
    assert all(isinstance(x, Arrival) for x in a.schedule())
    # and a different seed is a different workload
    c = LoadGen(mode=mode, seed=43, **_LG_KW)
    assert a.trace_bytes() != c.trace_bytes()


def test_modes_are_distinct_processes():
    """Same seed, different process: the traces must differ (the mode
    parameter is not cosmetic) and bursty must out-arrive calm poisson
    at equal mean rate parameters during its bursts."""
    traces = {m: LoadGen(mode=m, seed=7, **_LG_KW).trace_bytes()
              for m in LoadGen.MODES}
    assert len(set(traces.values())) == 3


@pytest.mark.parametrize("mode", list(LoadGen.MODES))
def test_same_seed_same_decisions(model, mode):
    """Two fresh engines, same seed, virtual clock, pinned costs: the
    admit/shed decision sequence — including shed reasons — replays
    exactly. This is the property that makes a loadgen regression
    bisectable."""
    reports = []
    for _ in range(2):
        vc = VirtualClock()
        eng = _engine(model, vc.now)
        lg = LoadGen(mode=mode, seed=5, **_LG_KW)
        reports.append(lg.run(eng, clock=vc, step_cost_ms=4.0))
    assert reports[0]["decisions"] == reports[1]["decisions"]
    assert reports[0]["shed"] == reports[1]["shed"]
    assert reports[0]["completed"] == reports[1]["completed"]
    assert reports[0]["offered"] > 0
    assert reports[0]["exceptions"] == 0
    assert reports[0]["leaked_kv_blocks"] == 0


def test_slo_admission_beats_depth_only_on_goodput(model):
    """The point of predictive admission: at the same offered load,
    the SLO-aware engine's goodput (completions inside the TTFT
    budget) beats the depth-only engine scored post-hoc against the
    same SLO — shedding doomed work early frees capacity for work
    that can still win."""
    lg_kw = dict(_LG_KW, rate=80.0, duration=0.6)   # well over capacity
    slo_ms = 40.0

    vc = VirtualClock()
    depth_only = _engine(model, vc.now, slo_ttft_ms=0.0, max_queue=32)
    base = LoadGen(mode="bursty", seed=9, **lg_kw).run(
        depth_only, clock=vc, step_cost_ms=4.0, slo_ttft_ms=slo_ms)

    vc2 = VirtualClock()
    slo_aware = _engine(model, vc2.now, slo_ttft_ms=slo_ms,
                        max_queue=32)
    aware = LoadGen(mode="bursty", seed=9, **lg_kw).run(
        slo_aware, clock=vc2, step_cost_ms=4.0)

    assert base["goodput_per_s"] is not None
    assert aware["goodput_per_s"] >= 1.2 * base["goodput_per_s"], \
        (base["goodput_per_s"], aware["goodput_per_s"])


# ----------------------------------------------------- trace round-trip
def test_from_trace_replays_own_schedule():
    """LoadGen.from_trace on a generator's own canonical trace yields
    the identical arrival schedule (the --replay fast path)."""
    import json
    a = LoadGen(mode="diurnal", seed=21, **_LG_KW)
    b = LoadGen.from_trace(json.loads(a.trace_bytes()))
    assert b.schedule() == a.schedule()
    assert b.trace_bytes() == a.trace_bytes()


def test_trace_convert_roundtrip_replays_decisions(model, tmp_path):
    """The incident-replay loop: a run's serving_request runlog events
    -> tools/trace_convert -> LoadGen.from_trace reproduces the
    workload — every offered request present with its prompt, budget,
    and priority — and a replay on a fresh engine (virtual clock,
    pinned costs) makes the identical admit/shed decisions."""
    import glob
    from tools.trace_convert import events_to_trace, load_events

    saved = pt.get_flags(["runlog_dir"])
    pt.set_flags({"runlog_dir": str(tmp_path)})
    try:
        vc = VirtualClock()
        lg = LoadGen(mode="bursty", seed=17, **_LG_KW)
        rep1 = lg.run(_engine(model, vc.now), clock=vc,
                      step_cost_ms=4.0)
    finally:
        pt.set_flags(saved)

    files = glob.glob(str(tmp_path / "runlog-*.jsonl*"))
    trace = events_to_trace(load_events(files))
    sched = lg.schedule()
    assert len(trace["arrivals"]) == rep1["offered"] == len(sched)
    assert [a[1:] for a in trace["arrivals"]] == \
        [[list(s.prompt), s.max_new_tokens, s.priority] for s in sched]

    lg2 = LoadGen.from_trace(trace)
    vc2 = VirtualClock()
    rep2 = lg2.run(_engine(model, vc2.now), clock=vc2,
                   step_cost_ms=4.0)
    assert rep2["offered"] == rep1["offered"]
    assert rep2["decisions"] == rep1["decisions"]
    assert rep2["shed"] == rep1["shed"]
    assert rep2["leaked_kv_blocks"] == 0


# ------------------------------------------------------------ elasticity
def test_autoscale_up_under_pressure_then_down(model):
    """Queue pressure grows the fleet inside the policy bounds; calm
    shrinks it — retiring replicas drain before dropping, so nothing
    in flight is shed by a scale-down."""
    policy = AutoscalePolicy(min_replicas=1, max_replicas=3,
                             queue_high=2.0, queue_low=0.5,
                             cooldown_steps=1)
    router = ReplicaRouter(model=model, n_replicas=1, autoscale=policy,
                           max_slots=2, max_len=32, buckets=[8],
                           max_queue=16)
    rng = np.random.RandomState(3)
    reqs = [router.submit(rng.randint(1, 97, size=4).tolist(),
                          max_new_tokens=4) for _ in range(12)]
    router.run_until_idle()
    assert all(r.state == "done" for r in reqs)
    s = router.stats()
    assert s["autoscale"]["scale_ups"] >= 1
    assert s["completed"] == 12
    # idle steps past the cooldown: shrink back to min_replicas
    for _ in range(10):
        router.step()
    s = router.stats()
    assert s["autoscale"]["scale_downs"] >= 1
    assert s["replicas"] == 1
    assert s["autoscale"]["retiring"] == 0


def test_router_drain_returns_shed_count(model):
    """drain() reports how many queued requests it gave up on — the
    scale-in/shutdown accounting hook."""
    clk = VirtualClock()
    router = ReplicaRouter(model=model, n_replicas=1, max_slots=1,
                           max_len=32, buckets=[8], max_queue=8,
                           slo_ttft_ms=50.0, slo_prefill_ms=1.0,
                           slo_tpot_ms=1.0, clock=clk.now)
    rng = np.random.RandomState(4)
    reqs = [router.submit(rng.randint(1, 97, size=4).tolist(),
                          max_new_tokens=2) for _ in range(3)]
    clk.advance(1.0)            # every deadline long expired in-queue
    shed = router.drain()
    assert shed == 3
    assert all(r.state == "shed" and r.shed_reason == "deadline"
               for r in reqs)
    assert router.stats()["shed"]["deadline"] == 3
    # a clean drain sheds nothing
    assert router.drain() == 0


# ------------------------------------------------------- chaos crossover
@pytest.mark.chaos
def test_chaos_goodput_degrades_gracefully(model):
    """Fault injection on submit + alloc: goodput drops versus the
    clean run, but zero unhandled exceptions escape, zero KV blocks
    leak, and offered == completed + sheds (every request accounted
    for)."""
    def run(spec):
        vc = VirtualClock()
        lg = LoadGen(mode="poisson", seed=11, **_LG_KW)
        if spec:
            with fault_scope(spec, seed=2):
                eng = _engine(model, vc.now)
                return lg.run(eng, clock=vc, step_cost_ms=4.0)
        eng = _engine(model, vc.now)
        return lg.run(eng, clock=vc, step_cost_ms=4.0)

    clean = run("")
    faulty = run("serving.submit:skip@0.25;serving.alloc:skip@0.15")
    for rep in (clean, faulty):
        assert rep["exceptions"] == 0
        assert rep["leaked_kv_blocks"] == 0
        accounted = rep["completed"] + rep["shed_total"] + sum(
            1 for d in rep["decisions"] if d[0] == "invalid")
        assert accounted == rep["offered"]
    assert faulty["shed"].get("fault", 0) > 0
    assert faulty["completed"] < clean["completed"]
    assert faulty["completed"] > 0       # degraded, not dead


# ------------------------------------------------------------ the static side
def test_predictor_admission_params_are_noops():
    """predict_serving_compiles with SLO/priority/autoscale parameters
    == without: admission is host-side queue surgery and must never
    change the compiled step set."""
    rounds = [[(list(range(1, 9)), 4), (list(range(1, 5)), 1)],
              [(list(range(1, 9)), 4)]]
    kw = dict(buckets=[8, 16], max_len=32, block_size=4)
    plain = predict_serving_compiles(rounds, **kw)
    assert plain  # non-trivial prediction
    decorated = predict_serving_compiles(
        rounds, slo_ttft_ms=250.0, priority_classes=[0, 1, 2],
        autoscale=(1, 4), **kw)
    assert decorated == plain
    with pytest.raises(ValueError, match="slo_ttft_ms"):
        predict_serving_compiles(rounds, slo_ttft_ms=-1.0, **kw)
    with pytest.raises(ValueError, match="priority_classes"):
        predict_serving_compiles(rounds, priority_classes=[], **kw)
    with pytest.raises(ValueError, match="autoscale"):
        predict_serving_compiles(rounds, autoscale=(3, 2), **kw)


def test_warmup_resets_learned_costs(model):
    """warmup() pays the compiles then drops the EWMAs, so the first
    measured admission decision isn't poisoned by trace time."""
    eng = ServingEngine(model, max_slots=2, max_len=32,
                        buckets=[8, 16], max_queue=8,
                        slo_ttft_ms=1000.0)
    warmup(eng)
    assert eng._prefill_ewma == {}
    assert eng._tpot_ewma is None
    assert eng.idle
    assert eng.predict_ttft_ms(prompt_len=4) == 0.0   # cold: optimistic


# ------------------------------------------------- decode-bearing mix
def test_decode_mix_trace_deterministic_and_greedy_unchanged():
    """sample_frac/tenant_mix draws are gated: a plain generator's RNG
    stream (and so its trace bytes) is untouched, while decode-bearing
    generators are seed-deterministic and round-trip through
    from_trace with their decode fields intact."""
    import json as _json
    plain = LoadGen(mode="bursty", seed=42, **_LG_KW)
    rows = _json.loads(plain.trace_bytes())["arrivals"]
    assert all(len(r) == 4 for r in rows)   # no decode fields leak in
    mix = dict(sample_frac=0.5,
               tenant_mix={"base": 0.5, "acme": 0.3, "zeta": 0.2})
    a = LoadGen(mode="bursty", seed=42, **_LG_KW, **mix)
    b = LoadGen(mode="bursty", seed=42, **_LG_KW, **mix)
    assert a.trace_bytes() == b.trace_bytes()
    sched = a.schedule()
    assert any(x.temperature > 0 for x in sched)
    assert any(x.tenant for x in sched)
    assert any(not x.tenant for x in sched)   # "base" maps to no tenant
    rt = LoadGen.from_trace(_json.loads(a.trace_bytes()))
    assert rt.schedule() == sched
    assert rt.trace_bytes() == a.trace_bytes()


def test_decode_mix_per_tenant_report_and_zero_leaks(model):
    """A two-tenant sampled burst on a virtual clock: per-tenant
    goodput reported, loadgen and engine tenant views agree on
    completions, zero leaked adapter pages."""
    from paddle_tpu.serving import make_adapter
    vc = VirtualClock()
    eng = _engine(model, vc.now, lora_rank=2, lora_max_adapters=2,
                  max_queue=16, slo_ttft_ms=200.0)
    cfg = model.gpt.cfg
    eng.load_adapter("acme", make_adapter(cfg, 2, seed=1))
    eng.load_adapter("zeta", make_adapter(cfg, 2, seed=2))
    lg = LoadGen(mode="poisson", seed=6, sample_frac=0.5,
                 tenant_mix={"base": 0.4, "acme": 0.3, "zeta": 0.3},
                 **_LG_KW)
    warmup(eng)
    report = lg.run(eng, clock=vc, step_cost_ms=4.0)
    assert report["exceptions"] == 0, report
    assert report["leaked_kv_blocks"] == 0
    assert report["leaked_lora_pages"] == 0
    pt_rep = report["per_tenant"]
    assert set(pt_rep) <= {"base", "acme", "zeta"}
    assert sum(t["offered"] for t in pt_rep.values()) == \
        report["offered"]
    assert sum(t["completed"] for t in pt_rep.values()) == \
        report["completed"]
    eng_tenants = eng.stats()["tenants"]
    for name, ts in pt_rep.items():
        if not ts["completed"]:
            continue
        if name == "base":   # engine's base bucket includes warmup
            assert eng_tenants[name]["completed"] >= ts["completed"], \
                (name, ts, eng_tenants)
        else:
            assert eng_tenants[name]["completed"] == ts["completed"], \
                (name, ts, eng_tenants)


# ----------------------------------------------- closed loop + chaos
def test_closed_loop_params_leave_open_loop_trace_untouched():
    """Closed-loop knobs draw from a SEPARATE RandomState: the
    arrival schedule and its trace bytes are byte-identical to the
    plain open-loop generator's — old seeds replay unchanged."""
    a = LoadGen(mode="poisson", seed=42, **_LG_KW)
    b = LoadGen(mode="poisson", seed=42, closed_loop=3,
                think_time_ms=(5.0, 20.0), **_LG_KW)
    assert a.trace_bytes() == b.trace_bytes()
    assert a.schedule() == b.schedule()
    with pytest.raises(ValueError):
        LoadGen(closed_loop=-1, **_LG_KW)
    with pytest.raises(ValueError):
        LoadGen(think_time_ms=(10.0, 5.0), **_LG_KW)


def test_closed_loop_run_deterministic_and_bounded(model):
    """N closed-loop clients: two identical runs make identical
    decisions, the report carries the client count, and offered
    never exceeds the open-loop schedule (clients skip arrivals
    they are still busy for)."""
    def run_once():
        vc = VirtualClock()
        lg = LoadGen(mode="poisson", seed=11, closed_loop=2,
                     think_time_ms=(2.0, 8.0), **_LG_KW)
        return lg.run(_engine(model, vc.now, max_queue=8), clock=vc,
                      step_cost_ms=4.0)

    r1, r2 = run_once(), run_once()
    assert r1["closed_loop"] == 2
    assert r1["offered"] == r2["offered"]
    assert r1["decisions"] == r2["decisions"]
    assert r1["makespan_s"] == r2["makespan_s"]
    assert r1["exceptions"] == 0 and r1["leaked_kv_blocks"] == 0


@pytest.mark.chaos
def test_chaos_replay_trace_roundtrip(model):
    """Chaos rows ride the trace: a generator with a kill/restart
    schedule round-trips through trace_bytes/from_trace, the run
    applies each event at its virtual instant, and the accounting
    identity completed + rehomed + shed == offered survives the
    crashes. A chaos-free generator's trace stays byte-identical."""
    import json
    plain = LoadGen(mode="poisson", seed=42, **_LG_KW)
    lg = LoadGen(mode="poisson", seed=42, **_LG_KW)
    assert lg.trace_bytes() == plain.trace_bytes()
    lg.chaos = [{"t": 0.2, "kind": "restart", "index": 0},
                {"t": 0.4, "kind": "kill", "index": 1}]
    assert lg.trace_bytes() != plain.trace_bytes()
    rt_trace = json.loads(lg.trace_bytes())
    assert rt_trace["chaos"] == [[0.2, "restart", 0],
                                 [0.4, "kill", 1]]
    lg2 = LoadGen.from_trace(rt_trace)
    assert lg2.chaos == lg.chaos
    assert lg2.trace_bytes() == lg.trace_bytes()

    vc = VirtualClock()
    rt = ReplicaRouter(model, n_replicas=2, max_slots=2, max_len=32,
                       buckets=[8, 16], max_queue=16, block_size=4,
                       clock=vc.now)
    warmup(rt)
    rep = lg2.run(rt, clock=vc, step_cost_ms=4.0)
    assert rep["chaos_applied"] == 2
    st = rt.stats()
    assert st["restarts"] == 1 and st["kills"] == 2
    errored = sum(1 for d in rep["decisions"]
                  if d[0] in ("invalid", "error"))
    assert rep["completed"] + rep["rehomed"] + rep["shed_total"] + \
        errored == rep["offered"]
    assert rep["exceptions"] == 0 and rep["leaked_kv_blocks"] == 0


def test_trace_convert_folds_kill_recover_into_restart():
    """events_to_trace carries chaos events on the arrivals' clock:
    a serving_replica_kill immediately recovered at the same instant
    folds into one restart row; a bare kill and a worker kill map to
    their own kinds."""
    from tools.trace_convert import events_to_trace
    events = [
        {"kind": "serving_request", "t": 10.0, "seq": 0,
         "prompt": [1, 2, 3], "max_new_tokens": 2, "priority": 1},
        {"kind": "serving_replica_kill", "t": 10.5, "seq": 1,
         "replica": 0, "rehomed": 1, "shed": 0},
        {"kind": "serving_replica_recover", "t": 10.5, "seq": 2,
         "replica": 0},
        {"kind": "serving_replica_kill", "t": 11.0, "seq": 3,
         "replica": 1, "rehomed": 0, "shed": 0},
        {"kind": "serving_worker_kill", "t": 11.5, "seq": 4,
         "role": "decode", "worker": 0},
    ]
    trace = events_to_trace(events)
    assert trace["chaos"] == [[0.5, "restart", 0],
                              [1.0, "kill", 1],
                              [1.5, "kill_decode", 0]]


def test_predictor_fault_tolerance_params_are_noops():
    """replica_kills/restarts/rehomed join the validated no-op family:
    kill is host-side teardown, restart reuses the per-model step
    cache at the same geometry, re-home is a bucket-bounded
    re-prefill — none may change the predicted compile set."""
    rounds = [[(list(range(1, 9)), 4), (list(range(1, 5)), 1)]]
    kw = dict(buckets=[8, 16], max_len=32, block_size=4,
              n_replicas=2)
    plain = predict_serving_compiles(rounds, **kw)
    chaotic = predict_serving_compiles(
        rounds, replica_kills=3, restarts=3, rehomed=7, **kw)
    assert chaotic == plain
    for bad in ("replica_kills", "restarts", "rehomed"):
        with pytest.raises(ValueError, match=bad):
            predict_serving_compiles(rounds, **{bad: -1}, **kw)


def test_soak_kill_spec_and_windows_units():
    """tools/soak.py pure units: the generated kill schedule spreads
    N one-shot virtual-time triggers evenly, and the window splitter
    buckets offered/completed by arrival/done instants."""
    from tools.soak import _windows, kill_spec
    assert kill_spec(7200.0, 2) == \
        "serving.replica:error@t>2400s;serving.replica:error@t>4800s"
    assert kill_spec(100.0, 0) == ""
    report = {"makespan_s": 10.0, "trace": [
        {"t": 1.0, "outcome": "done", "done_t": 2.0},
        {"t": 6.0, "outcome": "done", "done_t": 9.5},
        {"t": 6.2, "outcome": "shed", "done_t": None},
    ]}
    w = _windows(report, 2)
    assert [x["offered"] for x in w] == [1, 2]
    assert [x["completed"] for x in w] == [1, 1]
    assert w[1]["goodput_per_s"] == 0.2


# ------------------------------------------------- abandonment plane

_AB_KW = dict(mode="poisson", seed=1, closed_loop=4,
              think_time_ms=(2.0, 8.0), abandon_frac=0.2)


def test_abandonment_draws_are_seeded_and_trace_carried():
    """The abandonment stream is a dedicated RandomState: same seed
    same abandoners, thresholds always past the first token, and the
    trace rows carry the fraction in column 10."""
    import json as _json
    a = LoadGen(**_AB_KW, **_LG_KW)
    b = LoadGen(**_AB_KW, **_LG_KW)
    assert a.trace_bytes() == b.trace_bytes()
    sched = a.schedule()
    quitters = [x for x in sched if x.abandon_after > 0]
    assert len(quitters) >= 1
    assert all(0.25 <= q.abandon_after <= 0.75 for q in quitters)
    rows = _json.loads(a.trace_bytes())["arrivals"]
    assert all(len(r) > 9 for r in rows)
    assert sorted(r[9] for r in rows if r[9] > 0) == \
        sorted(q.abandon_after for q in quitters)


def test_abandonment_trace_roundtrip_byte_identical():
    """from_trace on an abandonment-bearing trace re-serializes byte
    for byte — the replay *is* the recorded workload."""
    import json as _json
    lg = LoadGen(**_AB_KW, **_LG_KW)
    raw = lg.trace_bytes()
    lg2 = LoadGen.from_trace(_json.loads(raw))
    assert lg2.trace_bytes() == raw
    assert any(a.abandon_after > 0 for a in lg2.schedule())


def test_abandon_free_seed_trace_unchanged_by_the_feature():
    """abandon_frac=0 must not perturb the arrival schedule of
    existing seeds (the draws come from a dedicated stream)."""
    plain = LoadGen(mode="poisson", seed=42, **_LG_KW)
    off = LoadGen(mode="poisson", seed=42, closed_loop=3,
                  abandon_frac=0.0, **_LG_KW)
    assert [a[:4] for a in plain.schedule()] == \
        [a[:4] for a in off.schedule()]


def test_closed_loop_abandonment_cancels_and_replays(model):
    """Closed-loop clients that abandon mid-decode land as cancels
    (reason="disconnect") with full reclaim — zero leaked KV blocks —
    the accounting identity extends with the canceled term, and a
    from_trace replay reproduces the same cancels decision for
    decision."""
    import json as _json

    def run(lg):
        vc = VirtualClock()
        return lg.run(_engine(model, vc.now, max_queue=8), clock=vc,
                      step_cost_ms=4.0)

    lg1 = LoadGen(**_AB_KW, **_LG_KW)
    r1 = run(lg1)
    assert r1["abandoned"] >= 1
    assert r1["canceled"] == {"disconnect": r1["abandoned"]}
    assert r1["canceled_total"] == r1["abandoned"]
    assert r1["leaked_kv_blocks"] == 0 and r1["exceptions"] == 0
    done = sum(1 for d, _ in r1["decisions"] if d == "done")
    shed = sum(1 for d, _ in r1["decisions"] if d == "shed")
    assert done + shed + r1["canceled_total"] == r1["offered"]

    lg2 = LoadGen.from_trace(_json.loads(lg1.trace_bytes()))
    lg2.closed_loop = lg1.closed_loop
    lg2.think_time_ms = lg1.think_time_ms
    r2 = run(lg2)
    assert r2["decisions"] == r1["decisions"]
    assert r2["canceled"] == r1["canceled"]
    assert r2["abandoned"] == r1["abandoned"]
    assert r2["leaked_kv_blocks"] == 0

#!/usr/bin/env python
"""Lint GSPMD sharding-rule tables against a model and a mesh.

Static pre-flight for ``to_static(mesh=..., param_rules=...)`` — runs
``distributed.sharding.lint_sharding_rules`` over a preset rule table
and the GPT benchmark model, with the mesh given as plain axis sizes
(no TPU devices needed):

    python tools/lint_sharding.py --preset gpt_tp --mesh dp=2,mp=2
    python tools/lint_sharding.py --preset gpt_tp+fully_sharded \\
        --mesh dp=4,mp=2 --strict --json

Findings (structured Diagnostics, same records as lint_program.py):
dead rules, earlier regexes shadowing later ones, silent
replicated-fallback on non-divisible dims, unknown mesh axes (ERROR),
oversized fully-replicated tensors — plus the per-device parameter
memory estimate under the fitted specs.

Exit status 1 on ERROR findings; --strict also fails on warnings.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# the tiny-but-structurally-faithful GPT used across CI gates
# (tools/obs_smoke.py, the serving tests): every TP rule family
# (qkv/out_proj/fc1/fc2/wte) has a live target. vocab_pad_to=2 pads the
# deliberately-awkward 97-row vocab to 98 so the vocab-parallel wte
# rule divides cleanly — `--preset gpt_tp --strict` runs warning-free
# (the old vocab-97 replicated fallback was the one expected finding).
GPT_CFG = dict(vocab_size=97, max_position_embeddings=64, hidden_size=32,
               num_layers=2, num_heads=4, ffn_hidden_size=64,
               vocab_pad_to=2)


def build_model():
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    pt.seed(0)
    return GPTForCausalLM(GPTConfig(**GPT_CFG))


def resolve_rules(preset: str):
    from paddle_tpu.distributed import sharding as sh
    presets = {
        "gpt_tp": sh.GPT_TENSOR_PARALLEL_RULES,
        "encoder_tp": sh.ENCODER_TENSOR_PARALLEL_RULES,
        "serving_tp": sh.SERVING_TP_RULES,
        "fully_sharded": sh.FULLY_SHARDED_RULES,
    }
    parts = [p.strip() for p in preset.split("+") if p.strip()]
    unknown = [p for p in parts if p not in presets]
    if unknown:
        raise SystemExit(
            f"unknown preset(s) {unknown}; available: "
            f"{sorted(presets)} (combine with '+', first wins)")
    rules = presets[parts[0]]
    for p in parts[1:]:
        rules = rules.merge(presets[p])
    return rules


def parse_mesh(text: str) -> dict:
    mesh = {}
    for tok in text.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" not in tok:
            raise SystemExit(
                f"bad --mesh entry {tok!r}: expected axis=size "
                f"(e.g. dp=2,mp=2)")
        axis, size = tok.split("=", 1)
        mesh[axis.strip()] = int(size)
    if not mesh:
        raise SystemExit("--mesh needs at least one axis=size entry")
    return mesh


def main(argv=None):
    ap = argparse.ArgumentParser(
        "lint_sharding",
        description="Static checks over sharding-rule tables.")
    ap.add_argument("--preset", default="gpt_tp",
                    help="rule table: gpt_tp | encoder_tp | serving_tp "
                         "| fully_sharded, or 'a+b' to merge (a wins) "
                         "[gpt_tp]")
    ap.add_argument("--mesh", default="dp=2,mp=2",
                    help="mesh axis sizes, axis=size,... [dp=2,mp=2]")
    ap.add_argument("--dtype-bytes", type=int, default=4,
                    help="bytes per parameter element [4]")
    ap.add_argument("--replicated-warn-mb", type=float, default=64.0,
                    help="warn on fully-replicated params above this "
                         "size [64]")
    ap.add_argument("--strict", action="store_true",
                    help="treat warnings as fatal too")
    ap.add_argument("--zero-stage", type=int, default=-1,
                    help="also estimate per-device optimizer-state "
                         "bytes under this ZeRO stage (0|1|2; -1 = "
                         "skip) [-1]")
    ap.add_argument("--zero-axis", default="dp",
                    help="mesh axis ZeRO shards optimizer state over "
                         "[dp]")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON report on stdout instead of text")
    args = ap.parse_args(argv)

    from paddle_tpu.distributed.sharding import (estimate_zero_opt_bytes,
                                                 lint_sharding_rules)

    mesh = parse_mesh(args.mesh)
    rules = resolve_rules(args.preset)
    model = build_model()
    result = lint_sharding_rules(
        rules, model, mesh, dtype_bytes=args.dtype_bytes,
        replicated_warn_mb=args.replicated_warn_mb)
    zero = None
    if args.zero_stage >= 0:
        if args.zero_axis not in mesh:
            raise SystemExit(
                f"--zero-axis {args.zero_axis!r} not in --mesh "
                f"{sorted(mesh)}")
        zero = estimate_zero_opt_bytes(
            model, mesh, rules, axis=args.zero_axis,
            stage=args.zero_stage, dtype_bytes=args.dtype_bytes)
    failed = bool(result.errors) or (args.strict
                                     and bool(result.warnings))

    if args.as_json:
        print(json.dumps({
            "ok": not failed,
            "preset": args.preset,
            "mesh": mesh,
            "errors": len(result.errors),
            "warnings": len(result.warnings),
            "diagnostics": [dataclasses.asdict(d)
                            for d in result.diagnostics],
            "rules": [dataclasses.asdict(r) if r.pattern is not None
                      else {**dataclasses.asdict(r), "pattern": None}
                      for r in _plain_rules(result.rules)],
            "params": [{"name": n, "shape": list(s), "spec": str(p)}
                       for n, s, p in result.params],
            "total_bytes": result.total_bytes,
            "per_device_bytes": result.per_device_bytes,
            "replicated_bytes": result.replicated_bytes,
            **({"zero": {"stage": args.zero_stage,
                         "axis": args.zero_axis, **zero}}
               if zero is not None else {}),
        }, indent=2))
        return 1 if failed else 0

    print(f"sharding lint: preset={args.preset} mesh={mesh} "
          f"({len(result.params)} params)")
    for i, r in enumerate(result.rules):
        label = (f"#{i} {r.pattern!r}" if r.pattern is not None
                 else "<default>")
        print(f"  {label}: spec={r.spec} matches={r.matches} "
              f"wins={r.wins}")
    for d in result.diagnostics:
        print(f"  {d}")
    mib = 1024 * 1024
    print(f"  parameter bytes: total={result.total_bytes} "
          f"({result.total_bytes / mib:.2f} MiB), "
          f"per-device={result.per_device_bytes} "
          f"({result.per_device_bytes / mib:.2f} MiB), "
          f"replicated={result.replicated_bytes}")
    if zero is not None:
        print(f"  ZeRO-{args.zero_stage} optimizer bytes (axis "
              f"{args.zero_axis!r}): total={zero['opt_bytes']}, "
              f"per-device={zero['opt_bytes_per_device']}")
    print(f"{'FAIL' if failed else 'ok'}: {len(result.errors)} error(s), "
          f"{len(result.warnings)} warning(s)")
    return 1 if failed else 0


def _plain_rules(reports):
    """dataclasses.asdict chokes on PartitionSpec fields — stringify."""
    out = []
    for r in reports:
        out.append(type(r)(pattern=r.pattern, spec=str(r.spec),
                           matches=r.matches, wins=r.wins))
    return out


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Lint Fluid programs with the static verifier (framework/analysis.py).

Two input modes:

    python tools/lint_program.py prog.json [more.json ...]
        Each file is a serialized Program (Program.to_json()); dead-code
        analysis is skipped because a serialized program carries no
        fetch list.

    python tools/lint_program.py --books
        Build the eight book programs (tools/book_programs.py) and lint
        each main+startup pair, with the training fetches as dead-code
        roots. This is the CI lint gate's zero-false-positive sweep.

--shapes adds static shape/dtype inference (the paddle_tpu/analysis
abstract interpreter, same as FLAGS_check_shapes) to the suite. --json
replaces the human-readable report with one JSON document on stdout
(per-program diagnostics as structured records) for tooling.

Exit status 1 if any program has ERROR diagnostics; --strict also fails
on warnings. --verbose prints every diagnostic of clean programs too.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def lint_one(label, program, feeds=(), fetches=None, strict=False,
             verbose=False, report=None):
    """Verify one program; print diagnostics; return True if it passes.

    With ``report`` (a list), append a structured record instead of
    printing (--json mode).
    """
    result = program.verify(feeds=feeds, fetches=fetches)
    failed = bool(result.errors) or (strict and result.warnings)
    if report is not None:
        report.append({
            "program": label,
            "ok": not failed,
            "errors": len(result.errors),
            "warnings": len(result.warnings),
            "diagnostics": [dataclasses.asdict(d) for d in result],
        })
        return not failed
    shown = result.diagnostics if (failed or verbose) else ()
    for d in shown:
        print(f"  {d}")
    print(f"{'FAIL' if failed else 'ok'}: {label} — {result.summary()}")
    return not failed


def lint_books(strict, verbose, report=None):
    from tools.book_programs import build_all
    ok = True
    for name, main, startup, fetches in build_all():
        ok &= lint_one(f"{name} (main)", main, fetches=fetches,
                       strict=strict, verbose=verbose, report=report)
        ok &= lint_one(f"{name} (startup)", startup, strict=strict,
                       verbose=verbose, report=report)
    return ok


def lint_files(paths, strict, verbose, report=None):
    from paddle_tpu.framework import Program
    ok = True
    for path in paths:
        with open(path) as f:
            program = Program.from_json(f.read())
        ok &= lint_one(path, program, strict=strict, verbose=verbose,
                       report=report)
    return ok


def main(argv=None):
    p = argparse.ArgumentParser(
        "lint_program",
        description="Static checks over serialized or book programs.")
    p.add_argument("files", nargs="*",
                   help="serialized Program JSON files to lint")
    p.add_argument("--books", action="store_true",
                   help="lint the eight book programs instead of files")
    p.add_argument("--strict", action="store_true",
                   help="treat warnings as fatal too")
    p.add_argument("--verbose", action="store_true",
                   help="print diagnostics even for passing programs")
    p.add_argument("--shapes", action="store_true",
                   help="also run static shape/dtype inference "
                        "(FLAGS_check_shapes / paddle_tpu/analysis)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON report on stdout instead of text")
    args = p.parse_args(argv)
    if args.books == bool(args.files):
        p.error("pass either JSON files or --books (exactly one)")
    if args.shapes:
        import paddle_tpu as pt
        pt.set_flags({"check_shapes": True})
    report = [] if args.as_json else None
    if args.books:
        ok = lint_books(args.strict, args.verbose, report=report)
    else:
        ok = lint_files(args.files, args.strict, args.verbose,
                        report=report)
    if report is not None:
        print(json.dumps({"ok": ok, "programs": report}, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Perf-regression ledger: one schema'd JSONL row per bench / loadgen /
soak run.

The device-cost observatory (paddle_tpu/observability/devprof.py)
measures a run; this module *remembers* it. Every row carries the
serving headline metrics (goodput, TTFT/TPOT p95, SLO attainment),
the devprof roofline summary (MFU, host-overhead share) when the run
profiled, the cost-table digest (so an XLA cost change is visible
even when a virtual clock hides it from wall metrics), and the git
revision — an append-only perf trajectory that
``tools/perf_regress.py`` enforces against a committed baseline.

Usage — in-process (loadgen ``--ledger``, soak ``--ledger``, bench
``BENCH_LEDGER``)::

    from tools import perf_ledger
    perf_ledger.append_report("perf_ledger.jsonl", report,
                              run="loadgen", label="ci-seeded")

or offline from a saved ``--json`` report::

    python tools/perf_ledger.py LEDGER.jsonl --from-report REPORT.json
    python tools/perf_ledger.py LEDGER.jsonl --show

Rows gate on metrics a seeded VirtualClock run reproduces exactly
(goodput / latency percentiles); MFU and the host share ride along as
informational fields because they sample wall time.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
from typing import Any, Dict, List, Optional

SCHEMA = 1

#: report keys copied verbatim into a row when present and numeric —
#: the deterministic headline metrics perf_regress.py can gate on
METRIC_KEYS = ("goodput_per_s", "ttft_ms_p95", "tpot_ms_p95",
               "slo_attainment", "completed", "offered", "shed_total",
               "new_compiles_after_warmup")

#: devprof-section keys carried as informational fields (wall-clock
#: sampled — never gated by default)
DEVPROF_KEYS = ("mfu", "host_overhead_share", "device_frac",
                "samples", "dispatches")


def git_rev() -> Optional[str]:
    """Short HEAD revision of the repo this file lives in, or None
    outside a checkout (rows stay appendable from exported trees)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else None
    except Exception:
        return None


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return v


def _live_cost_digest() -> Optional[str]:
    """The in-process cost-table digest, when the observatory has
    captured anything this process (None otherwise — e.g. the offline
    ``--from-report`` path, which falls back to the report's copy)."""
    try:
        from paddle_tpu.observability import devprof
        return devprof.cost_digest()
    except Exception:
        return None


def make_row(report: Dict[str, Any], run: str = "loadgen",
             label: str = "", ts: Optional[str] = None,
             rev: Optional[str] = None) -> Dict[str, Any]:
    """Fold a loadgen/soak/bench report dict into one ledger row."""
    row: Dict[str, Any] = {
        "schema": SCHEMA,
        "ts": ts if ts is not None else datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "git_rev": rev if rev is not None else git_rev(),
        "run": str(run),
    }
    if label:
        row["label"] = str(label)
    for k in METRIC_KEYS:
        v = _num(report.get(k))
        if v is not None:
            row[k] = v
    dp = report.get("devprof")
    if isinstance(dp, dict):
        for k in DEVPROF_KEYS:
            v = _num(dp.get(k))
            if v is not None:
                row[k] = v
    digest = _live_cost_digest()
    if digest is None and isinstance(dp, dict):
        digest = dp.get("cost_digest")
    row["cost_digest"] = digest
    return row


def append_row(path: str, row: Dict[str, Any]) -> Dict[str, Any]:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")
    return row


def append_report(path: str, report: Dict[str, Any],
                  run: str = "loadgen", label: str = ""
                  ) -> Dict[str, Any]:
    """The one-call hook the drivers use: make a row, append it,
    return it (so reports can embed what they logged)."""
    return append_row(path, make_row(report, run=run, label=label))


def read_rows(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path, "r", encoding="utf-8") as f:
        for ln, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{ln}: bad ledger line: {e}")
            if not isinstance(row, dict):
                raise ValueError(f"{path}:{ln}: row is not an object")
            rows.append(row)
    return rows


def latest(path: str) -> Optional[Dict[str, Any]]:
    rows = read_rows(path)
    return rows[-1] if rows else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="append to / inspect the perf-regression ledger")
    ap.add_argument("ledger", help="JSONL ledger path")
    ap.add_argument("--from-report", default="", metavar="REPORT.json",
                    help="append one row folded from a saved --json "
                         "report ('-' reads stdin)")
    ap.add_argument("--run", default="loadgen",
                    help="run kind recorded on the row "
                         "(loadgen | soak | bench; default loadgen)")
    ap.add_argument("--label", default="",
                    help="free-form scenario label for the row")
    ap.add_argument("--show", action="store_true",
                    help="print every row, one JSON object per line")
    args = ap.parse_args(argv)

    if args.from_report:
        if args.from_report == "-":
            report = json.load(sys.stdin)
        else:
            with open(args.from_report, "r", encoding="utf-8") as f:
                report = json.load(f)
        row = append_report(args.ledger, report, run=args.run,
                            label=args.label)
        print(json.dumps(row, sort_keys=True))
        return 0
    if args.show:
        for row in read_rows(args.ledger):
            print(json.dumps(row, sort_keys=True))
        return 0
    ap.error("nothing to do: pass --from-report or --show")
    return 2


if __name__ == "__main__":
    sys.exit(main())

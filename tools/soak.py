#!/usr/bin/env python
"""Virtual-clock chaos soak: hours of diurnal fleet traffic, with a
seeded kill/restart schedule, in seconds of wall time.

The harness composes three replayable pieces:

- :class:`tools.loadgen.LoadGen` in ``diurnal`` mode on a
  :class:`VirtualClock` — a whole traffic "day" (``--hours``)
  compresses into seconds because the loop jumps idle gaps and only
  pays real CPU per scheduler step;
- the fault injector's virtual-time triggers: the kill schedule is a
  plain ``FLAGS_fault_spec`` string of ``serving.replica:error@t>Ns``
  clauses with the injector's clock pointed at the *same* virtual
  clock (``resilience.set_time_source``), so a given ``--seed`` +
  ``--hours`` + ``--kills`` replays the exact same crashes at the
  exact same virtual instants, byte for byte;
- the :class:`ReplicaRouter` fault-tolerance plane: each injected
  crash kills a replica mid-flight (queued work re-homes, in-flight
  decodes re-prefill from committed tokens on survivors) and — under
  ``FLAGS_serving_auto_restart`` — brings a replacement up at the
  same geometry.

Throughout, the harness continuously asserts the **graceful
degradation contract**:

- goodput stays > 0 in every traffic window that offered load
  (``--windows`` equal slices of the run);
- the accounting identity ``completed + rehomed + shed + canceled ==
  offered`` holds (every request's fate is recorded, nothing vanishes
  in a crash or a client hang-up);
- zero leaked KV blocks and zero leaked LoRA pages after the fleet
  drains (dead replicas included);
- zero unhandled exceptions;
- zero new XLA compiles after warmup — and
  ``analysis.recompile.predict_serving_compiles`` proves statically
  that the kill/restart/re-home/cancel/hedge counts are no-ops
  (predicting with them == predicting without);
- with hedged prefill on (``--hedge-ms``), fired hedges stay inside
  the token-bucket envelope — ``--expect-hedge-budget-respected``
  gates ``fired <= 1 + budget * offered``; with abandonment on
  (``--closed-loop N --abandon-frac F``) the canceled bucket joins
  the identity and the fleet still drains leak-free;
- under ``FLAGS_sanitize_locks=1`` (+ ``--expect-sanitizer-clean``),
  zero lock-order cycles and zero guarded-state violations from the
  concurrency sanitizer across every kill/re-home/scrape — the soak
  record carries ``analysis.sanitizer_report()`` either way.

``--sweep`` reruns the identical workload + kill schedule across
:class:`AutoscalePolicy` bounds and emits the cost-vs-goodput
frontier (replica-seconds provisioned vs SLO-met completions/s) —
written to ``--out`` (e.g. ``BENCH_r12.json``).

Every request carries a distributed trace (``observability.tracing``,
virtual-clock timestamps), so the per-window report also includes SLO
**burn rate** ((1 - attainment) / (1 - ``--slo-target``)) and TTFT
percentiles from the trace store, the record includes the fleet blame
summary (which latency component dominates the E2E p95 tail), and
``--trace-out`` exports the whole arm as Perfetto-loadable
chrome-trace JSON — byte-identical across same-seed runs.

CLI gates (``--expect-*``) exit nonzero on violation, so CI can hold
the line::

  JAX_PLATFORMS=cpu python tools/soak.py --hours 2 --kills 2 \
      --replicas 2 --seed 0 --json --expect-kills-min 2 \
      --expect-goodput-every-window --expect-zero-leaks \
      --expect-zero-new-compiles --expect-identity
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_SERVING = ("serving_", "decode_", "verify_")


def kill_spec(duration: float, kills: int,
              site: str = "serving.replica") -> str:
    """The seeded kill schedule as a fault-spec string: ``kills``
    crashes spread evenly across the run (at 1/(k+1), 2/(k+1), ...
    of ``duration``), each a one-shot virtual-time trigger."""
    ts = [int(duration * (i + 1) / (kills + 1))
          for i in range(kills)]
    return ";".join(f"{site}:error@t>{t}s" for t in ts)


def _hedge_budget_flag() -> float:
    from paddle_tpu import flags as _fl
    return float(_fl.get_flags(["serving_hedge_budget"])
                 ["serving_hedge_budget"])


def _windows(report: dict, n: int) -> List[dict]:
    """Per-window offered/completed/goodput over [0, makespan]: the
    continuous form of the degradation contract. Completions land in
    the window their ``done_t`` falls in."""
    span = max(report["makespan_s"], 1e-9)
    w = span / n
    out = [{"window": i, "t0": round(i * w, 3),
            "t1": round((i + 1) * w, 3), "offered": 0,
            "completed": 0, "goodput_per_s": 0.0}
           for i in range(n)]
    for rec in report["trace"]:
        wi = min(int(rec["t"] / w), n - 1)
        out[wi]["offered"] += 1
        if rec["outcome"] == "done" and rec.get("done_t") is not None:
            wj = min(int(rec["done_t"] / w), n - 1)
            out[wj]["completed"] += 1
    for row in out:
        row["goodput_per_s"] = round(row["completed"] / w, 4)
    return out


def run_arm(model, lg, args, *,
            autoscale: Optional[Tuple[int, int]] = None,
            fault_spec: str = "") -> dict:
    """One soak arm: fresh fleet, same schedule, same kill times."""
    from paddle_tpu import observability as _obs
    from paddle_tpu.observability import tracing as _tracing
    from paddle_tpu.resilience import fault_scope
    from paddle_tpu.serving import AutoscalePolicy, ReplicaRouter
    from tools.loadgen import VirtualClock, warmup

    # fresh trace store per arm: every span in the export belongs to
    # THIS run, and two same-seed soaks export byte-identical traces
    _tracing.reset()
    vc = VirtualClock()
    rt = ReplicaRouter(
        model, n_replicas=args.replicas,
        autoscale=(None if autoscale is None else AutoscalePolicy(
            min_replicas=autoscale[0], max_replicas=autoscale[1])),
        max_slots=args.slots, max_len=args.max_len,
        max_queue=args.max_queue,
        buckets=[int(b) for b in args.buckets.split(",")],
        clock=vc.now, slo_ttft_ms=args.slo_ttft_ms,
        slo_prefill_ms=args.slo_prefill_ms,
        slo_tpot_ms=args.slo_tpot_ms,
        hedge_ms=args.hedge_ms, hedge_budget=args.hedge_budget)
    # (virtual time, live replicas) samples -> provisioned-cost
    # integral; gap jumps charge the count at the previous sample
    samples: List[Tuple[float, int]] = []

    def on_step(_i):
        samples.append((vc.now(), len(rt.engines)))

    with fault_scope(fault_spec, seed=args.fault_seed,
                     time_source=vc.now):
        # warmup INSIDE the scope: entering it bumps the flag-plane
        # version, which invalidates every step_entry — warming up
        # outside would hand the run a cold compile cache. Safe
        # because the virtual clock doesn't advance during warmup, so
        # @t>Ns triggers stay dormant (injector elapsed stays 0).
        warmup(rt)
        base = {k: v["count"] for k, v in _obs.compiles().items()
                if k.startswith(_SERVING)}
        samples.append((vc.now(), len(rt.engines)))
        report = lg.run(rt, clock=vc, step_cost_ms=args.step_ms,
                        slo_ttft_ms=args.slo_ttft_ms or None,
                        include_trace=True,
                        max_steps=args.max_steps, on_step=on_step)
    report["new_compiles_after_warmup"] = sum(
        v["count"] - base.get(k, 0)
        for k, v in _obs.compiles().items() if k.startswith(_SERVING))
    samples.append((vc.now(), len(rt.engines)))
    cost = sum((samples[i + 1][0] - samples[i][0]) * samples[i][1]
               for i in range(len(samples) - 1))
    st = rt.stats()
    report["replica_seconds"] = round(cost, 3)
    report["kills"] = st["kills"]
    report["restarts"] = st["restarts"]
    report["fleet_rehomed"] = st["rehomed"]
    report["health"] = st["health"]
    report["replicas_final"] = st["replicas"]
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="virtual-clock chaos soak for the serving fleet")
    ap.add_argument("--model", default="gpt2-tiny")
    ap.add_argument("--hours", type=float, default=2.0,
                    help="simulated traffic span (virtual hours)")
    ap.add_argument("--rate", type=float, default=0.02,
                    help="mean arrival rate, requests per VIRTUAL "
                    "second (0.02 over 2h ~ 144 requests)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fault-seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--kills", type=int, default=2,
                    help="replica crashes injected, spread evenly "
                    "across the run (serving.replica@t>Ns triggers)")
    ap.add_argument("--fault-spec", default=None,
                    help="override the generated kill schedule with "
                    "an explicit FLAGS_fault_spec string")
    ap.add_argument("--windows", type=int, default=8,
                    help="equal traffic windows the degradation "
                    "contract is asserted over")
    ap.add_argument("--sweep", default="",
                    metavar="MIN:MAX,MIN:MAX",
                    help="autoscale bounds to sweep for the cost-vs-"
                    "goodput frontier (e.g. '1:2,2:2,2:4')")
    ap.add_argument("--prompt-tokens", default="4:16", metavar="LO:HI")
    ap.add_argument("--new-tokens", default="2:8", metavar="LO:HI")
    ap.add_argument("--sample-frac", type=float, default=0.0)
    ap.add_argument("--closed-loop", type=int, default=0,
                    help="> 0 runs N closed-loop clients instead of "
                    "open-loop release (needed for --abandon-frac)")
    ap.add_argument("--abandon-frac", type=float, default=0.0,
                    help="fraction of closed-loop clients that hang "
                    "up mid-decode (fleet cancels; the canceled "
                    "bucket joins the accounting identity)")
    ap.add_argument("--hedge-ms", type=float, default=0.0,
                    help="router hedged prefill threshold/delay in "
                    "virtual ms (> 0 fixed, -1 auto TTFT p95, 0 off)")
    ap.add_argument("--hedge-budget", type=float, default=None,
                    help="hedge token-bucket refill per offered "
                    "request (default FLAGS_serving_hedge_budget)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--buckets", default="16,32")
    ap.add_argument("--megastep", type=int, default=1, metavar="N",
                    help="> 1 soaks with device-resident decode "
                    "megasteps (FLAGS_serving_megastep): N decode "
                    "iterations per dispatch, one host commit per "
                    "megastep; tokens stay byte-identical to N=1")
    ap.add_argument("--dispatch-threads", type=int, default=0,
                    metavar="T", help="> 0 steps the fleet from a "
                    "bounded pool of T threads "
                    "(FLAGS_serving_dispatch_threads); 0 keeps the "
                    "serial deterministic loop")
    ap.add_argument("--step-ms", type=float, default=5.0,
                    help="virtual cost per scheduler step")
    ap.add_argument("--slo-ttft-ms", type=float, default=60000.0,
                    help="TTFT SLO in virtual ms (goodput numerator)")
    ap.add_argument("--slo-prefill-ms", type=float, default=20.0)
    ap.add_argument("--slo-tpot-ms", type=float, default=5.0)
    ap.add_argument("--max-steps", type=int, default=500_000)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default="",
                    help="write the soak record (windows + frontier) "
                    "here, e.g. BENCH_r12.json")
    ap.add_argument("--ledger", default="", metavar="PATH",
                    help="append the primary arm's headline metrics "
                    "as one tools/perf_ledger.py JSONL row")
    ap.add_argument("--trace-out", default="", metavar="PATH",
                    help="export the primary arm's per-request span "
                    "traces as Perfetto-loadable chrome-trace JSON "
                    "(virtual-clock timestamps: byte-identical across "
                    "same-seed runs)")
    ap.add_argument("--slo-target", type=float, default=0.99,
                    help="SLO attainment target the per-window burn "
                    "rate is measured against (burn = (1 - "
                    "attainment) / (1 - target); default 0.99)")
    ap.add_argument("--expect-kills-min", type=int, default=None,
                    help="exit 1 unless the primary arm killed+"
                    "restarted at least this many replicas")
    ap.add_argument("--expect-goodput-every-window",
                    action="store_true",
                    help="exit 1 if any window that offered load "
                    "completed nothing")
    ap.add_argument("--expect-zero-leaks", action="store_true")
    ap.add_argument("--expect-zero-new-compiles", action="store_true")
    ap.add_argument("--expect-identity", action="store_true",
                    help="exit 1 unless completed + rehomed + shed + "
                    "canceled (+ rejects/errors) == offered")
    ap.add_argument("--expect-hedge-budget-respected",
                    action="store_true",
                    help="exit 1 unless fired hedges <= 1 + "
                    "hedge_budget * offered (the token-bucket "
                    "envelope; requires --hedge-ms)")
    ap.add_argument("--expect-sanitizer-clean", action="store_true",
                    help="exit 1 unless FLAGS_sanitize_locks was on, "
                    "the sanitizer instrumented lock traffic, and it "
                    "recorded zero lock-order cycles and zero "
                    "guarded-state violations")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as pt
    from paddle_tpu.analysis import predict_serving_compiles
    from paddle_tpu.models.gpt import GPT_CONFIGS, GPTForCausalLM
    from tools.loadgen import LoadGen

    if args.megastep < 1:
        print("FAIL: --megastep must be >= 1", file=sys.stderr)
        return 1
    if args.dispatch_threads < 0:
        print("FAIL: --dispatch-threads must be >= 0", file=sys.stderr)
        return 1
    if args.megastep > 1 or args.dispatch_threads > 0:
        # flags reach every engine the arms construct, including
        # watchdog-restarted replicas mid-soak
        pt.set_flags({"serving_megastep": args.megastep,
                      "serving_dispatch_threads": args.dispatch_threads})

    duration = args.hours * 3600.0
    cfg = GPT_CONFIGS[args.model]
    pt.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()

    def parse_range(s):
        lo, hi = (int(p) for p in s.split(":"))
        return lo, hi

    def fresh_lg() -> LoadGen:
        # one generator per arm (records are per-run) — same seed,
        # so every arm fights the byte-identical schedule
        return LoadGen(
            mode="diurnal", rate=args.rate, duration=duration,
            seed=args.seed, vocab_size=cfg.vocab_size,
            prompt_tokens=parse_range(args.prompt_tokens),
            new_tokens=parse_range(args.new_tokens),
            sample_frac=args.sample_frac,
            closed_loop=args.closed_loop,
            abandon_frac=args.abandon_frac)

    spec = (args.fault_spec if args.fault_spec is not None
            else kill_spec(duration, args.kills))

    # ---- primary arm: fixed fleet under the kill schedule ----------
    lg = fresh_lg()
    report = run_arm(model, lg, args, fault_spec=spec)
    windows = _windows(report, args.windows)

    # ---- tracing view of the same arm: burn rate + blame -----------
    # (snapshot BEFORE the sweep arms reset the trace store)
    from paddle_tpu.observability import tracing as _tracing
    snaps = _tracing.window_snapshots(
        args.windows, max(report["makespan_s"], 1e-9),
        slo_ttft_ms=args.slo_ttft_ms, slo_target=args.slo_target)
    for row, snap in zip(windows, snaps):
        row["attainment"] = snap["attainment"]
        row["burn_rate"] = snap["burn_rate"]
        row["ttft_ms_p50"] = snap["ttft_ms_p50"]
        row["ttft_ms_p95"] = snap["ttft_ms_p95"]
    blame = _tracing.blame_summary()
    if args.trace_out:
        _tracing.export_chrome_trace(args.trace_out)
    trace = report.pop("trace")
    errored = sum(1 for d in report["decisions"]
                  if d[0] in ("invalid", "error"))
    report.pop("decisions")
    identity_ok = (report["completed"] + report["rehomed"] +
                   report["shed_total"] + report["canceled_total"] +
                   errored == report["offered"])

    # ---- the static half of the zero-new-compiles proof ------------
    lg_workload = [[(list(a.prompt), a.max_new_tokens)
                    for a in lg.schedule()]]
    pkw = dict(buckets=[int(b) for b in args.buckets.split(",")],
               max_len=args.max_len, n_replicas=args.replicas,
               slo_ttft_ms=args.slo_ttft_ms,
               megastep=args.megastep)
    plain_pred = predict_serving_compiles(lg_workload, **pkw)
    hedges_fired = int(report.get("hedges", {}).get("fired", 0))
    chaos_pred = predict_serving_compiles(
        lg_workload, replica_kills=report["kills"],
        restarts=report["restarts"], rehomed=report["rehomed"],
        cancel=report["canceled_total"], hedge=hedges_fired,
        **pkw)
    predictor_noop = (chaos_pred == plain_pred)

    # ---- autoscale sweep: cost-vs-goodput frontier -----------------
    frontier = [{
        "arm": f"fixed-{args.replicas}",
        "autoscale": None,
        "replica_seconds": report["replica_seconds"],
        "goodput_per_s": report["goodput_per_s"],
        "slo_attainment": report["slo_attainment"],
        "completed": report["completed"],
        "rehomed": report["rehomed"],
        "shed_total": report["shed_total"],
        "kills": report["kills"],
        "restarts": report["restarts"],
    }]
    for bounds_s in [b for b in args.sweep.split(",") if b]:
        lo, hi = (int(p) for p in bounds_s.split(":"))
        arm = run_arm(model, fresh_lg(), args, autoscale=(lo, hi),
                      fault_spec=spec)
        arm.pop("trace")
        arm.pop("decisions")
        frontier.append({
            "arm": f"auto-{lo}:{hi}", "autoscale": [lo, hi],
            "replica_seconds": arm["replica_seconds"],
            "goodput_per_s": arm["goodput_per_s"],
            "slo_attainment": arm["slo_attainment"],
            "completed": arm["completed"],
            "rehomed": arm["rehomed"],
            "shed_total": arm["shed_total"],
            "kills": arm["kills"],
            "restarts": arm["restarts"],
        })
        if arm["exceptions"] or arm["leaked_kv_blocks"] or \
                arm["new_compiles_after_warmup"]:
            print(f"FAIL: sweep arm {bounds_s} broke the contract: "
                  f"{arm['exceptions']} exceptions, "
                  f"{arm['leaked_kv_blocks']} leaked blocks, "
                  f"{arm['new_compiles_after_warmup']} new compiles",
                  file=sys.stderr)
            return 1

    # ---- concurrency sanitizer verdict over every arm --------------
    from paddle_tpu.analysis import concurrency as _ccz
    san = _ccz.sanitizer_report()

    out = {
        "bench": "soak_fleet_fault_tolerance",
        "model": args.model,
        "simulated_hours": args.hours,
        "seed": args.seed,
        "fault_spec": spec,
        "report": report,
        "windows": windows,
        "blame": blame,
        "slo_target": args.slo_target,
        "burn_rate": [row["burn_rate"] for row in windows],
        "predictor_noop": predictor_noop,
        "identity_ok": identity_ok,
        "hedge_budget_ok": (
            hedges_fired <= 1 + (args.hedge_budget if args.hedge_budget
                                 is not None else _hedge_budget_flag())
            * report["offered"]) if args.hedge_ms != 0.0 else None,
        "frontier": frontier,
        "sanitizer": san,
    }
    if args.trace_out:
        out["trace_out"] = args.trace_out
    if args.ledger:
        from tools import perf_ledger
        out["ledger_row"] = perf_ledger.append_report(
            args.ledger, report, run="soak")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
            f.write("\n")
    if args.json:
        print(json.dumps(out))
    else:
        for k in ("offered", "completed", "rehomed", "shed_total",
                  "canceled_total", "abandoned",
                  "kills", "restarts", "goodput_per_s",
                  "slo_attainment", "replica_seconds",
                  "leaked_kv_blocks", "exceptions",
                  "new_compiles_after_warmup"):
            print(f"{k}: {report[k]}")
        for row in windows:
            burn = row.get("burn_rate")
            print(f"window {row['window']} "
                  f"[{row['t0']:>8.1f}s..{row['t1']:>8.1f}s): "
                  f"offered {row['offered']:>3} completed "
                  f"{row['completed']:>3} goodput "
                  f"{row['goodput_per_s']}/s burn "
                  f"{'-' if burn is None else burn}")
        if blame["requests"]:
            print(f"tail blame: {blame['tail_dominant']} dominates "
                  f"the E2E p95 tail ({blame['e2e_ms_p95']} ms over "
                  f"{blame['requests']} traced requests)")
        for row in frontier:
            print(f"frontier {row['arm']}: "
                  f"{row['replica_seconds']} replica-s -> "
                  f"{row['goodput_per_s']}/s goodput")
        if san["enabled"]:
            print(f"sanitizer: {san['lock_acquires']} acquires over "
                  f"{san['locks_tracked']} locks, "
                  f"{san['order_edges']} order edges, "
                  f"{len(san['cycles'])} cycles, "
                  f"{len(san['violations'])} violations")

    ok = True
    if args.expect_kills_min is not None and \
            report["kills"] < args.expect_kills_min:
        print(f"FAIL: kills {report['kills']} < "
              f"{args.expect_kills_min}", file=sys.stderr)
        ok = False
    if args.expect_goodput_every_window:
        for row in windows:
            if row["offered"] > 0 and row["completed"] == 0:
                print(f"FAIL: window {row['window']} offered "
                      f"{row['offered']} but completed 0",
                      file=sys.stderr)
                ok = False
    if args.expect_zero_leaks:
        if report["leaked_kv_blocks"] != 0:
            print(f"FAIL: leaked_kv_blocks = "
                  f"{report['leaked_kv_blocks']}", file=sys.stderr)
            ok = False
        if report.get("leaked_lora_pages"):
            print(f"FAIL: leaked_lora_pages = "
                  f"{report['leaked_lora_pages']}", file=sys.stderr)
            ok = False
    if args.expect_zero_new_compiles:
        if report["new_compiles_after_warmup"] != 0:
            print(f"FAIL: new_compiles_after_warmup = "
                  f"{report['new_compiles_after_warmup']}",
                  file=sys.stderr)
            ok = False
        if not predictor_noop:
            print(f"FAIL: predictor says kills/restarts/re-homes "
                  f"change compile counts:\n  plain {plain_pred}\n"
                  f"  chaos {chaos_pred}", file=sys.stderr)
            ok = False
    if args.expect_sanitizer_clean:
        if not san["enabled"] or san["lock_acquires"] == 0:
            print("FAIL: --expect-sanitizer-clean needs "
                  "FLAGS_sanitize_locks=1 and instrumented lock "
                  f"traffic (enabled={san['enabled']}, acquires="
                  f"{san['lock_acquires']})", file=sys.stderr)
            ok = False
        if san["cycles"] or san["violations"]:
            print(f"FAIL: sanitizer saw {len(san['cycles'])} lock-"
                  f"order cycle(s), {len(san['violations'])} guarded-"
                  f"state violation(s): {san['cycles']} "
                  f"{san['violations']}", file=sys.stderr)
            ok = False
    if args.expect_identity and not identity_ok:
        print(f"FAIL: completed {report['completed']} + rehomed "
              f"{report['rehomed']} + shed {report['shed_total']} + "
              f"canceled {report['canceled_total']} + "
              f"errors {errored} != offered {report['offered']}",
              file=sys.stderr)
        ok = False
    if args.expect_hedge_budget_respected:
        if args.hedge_ms == 0.0 or "hedges" not in report:
            print("FAIL: --expect-hedge-budget-respected needs "
                  "--hedge-ms (no hedging ran)", file=sys.stderr)
            ok = False
        else:
            frac = (args.hedge_budget if args.hedge_budget is not None
                    else _hedge_budget_flag())
            cap = 1 + frac * report["offered"]
            if hedges_fired > cap:
                print(f"FAIL: {hedges_fired} hedges fired > budget "
                      f"envelope 1 + {frac} * {report['offered']} = "
                      f"{cap}", file=sys.stderr)
                ok = False
    if report["exceptions"]:
        print(f"FAIL: {report['exceptions']} unhandled exceptions",
              file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""CI train->serve gate: ZeRO optimizer plane + live weight hot-swap.

Two halves, matching the two halves of the loop:

  - **train**: a 2-step ZeRO train run must match the unsharded
    baseline loss-for-loss on a 1x1 mesh in-process, then again on a
    dp=2 mesh in a subprocess carved out with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=2`` — where the
    per-device optimizer bytes must land at ~1/2 of the total (the
    ZeRO memory win, measured from live ``addressable_shards``, not
    estimated);
  - **serve**: the trained weights are published through
    ``CheckpointSaver`` (``zero.save_train_state``) and hot-swapped
    into a *running* ServingEngine
    (``swap_weights(zero.weights_from_checkpoint(...))``): post-swap
    tokens must equal greedy decoding on the trained model, with ZERO
    new XLA compiles observed by the tracker.

Run from the repo root:  JAX_PLATFORMS=cpu python tools/zero_smoke.py
(the dp=2 half respawns itself; ``--dp2`` runs just that half in an
already-carved-out process).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

CFG = dict(vocab_size=128, max_position_embeddings=32, hidden_size=32,
           num_layers=2, num_heads=4, ffn_hidden_size=64)
STEPS = 2


def _build(seed=0):
    import paddle_tpu as pt
    from paddle_tpu.framework import unique_name
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.optimizer import AdamW
    with unique_name.guard():
        pt.seed(seed)
        model = GPTForCausalLM(GPTConfig(**CFG))
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    return model, opt


def _train_fn(model, opt):
    def train_step(ids, labels):
        loss = model(ids, labels=labels)
        model.clear_gradients()
        loss.backward()
        opt.step()
        return loss
    return train_step


def _data(steps=STEPS, batch=4, seq=16, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        ids = rng.randint(0, CFG["vocab_size"], (batch, seq))
        out.append((ids.astype(np.int32),
                    np.roll(ids, -1, axis=1).astype(np.int32)))
    return out


def _mesh(shape):
    import numpy as np
    import jax
    from jax.sharding import Mesh
    n = int(np.prod(shape))
    return Mesh(np.asarray(jax.devices()[:n]).reshape(shape),
                ("dp", "mp"))


def _parity(mesh_shape, stage, arg_specs=None):
    """ZeRO step vs unsharded step over STEPS batches; returns the
    ZeRO wrapper's byte report."""
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from paddle_tpu import jit
    from paddle_tpu.distributed import zero

    ref_model, ref_opt = _build()
    ref_step = jit.to_static(_train_fn(ref_model, ref_opt),
                             layers=[ref_model], optimizers=[ref_opt])
    z_model, z_opt = _build()
    z_step = zero.zero_train_step(
        _train_fn(z_model, z_opt), layers=[z_model], optimizers=[z_opt],
        mesh=_mesh(mesh_shape), stage=stage,
        arg_specs=arg_specs or (P("dp"), P("dp")))
    for i, (ids, labels) in enumerate(_data()):
        ref_loss = float(np.asarray(ref_step(ids, labels).value))
        z_loss = float(np.asarray(z_step(ids, labels).value))
        assert np.isfinite(z_loss), (stage, i, z_loss)
        assert abs(z_loss - ref_loss) <= 2e-3 * abs(ref_loss), \
            f"stage {stage} step {i}: {z_loss} vs {ref_loss}"
    return z_step.byte_report()


def run_dp2() -> int:
    import jax
    assert jax.device_count() >= 2, (
        f"dp=2 half needs 2 devices, got {jax.device_count()} — run "
        "under XLA_FLAGS=--xla_force_host_platform_device_count=2")
    for stage in (1, 2):
        rep = _parity((2, 1), stage)
        ratio = rep["opt_bytes_per_device"] / rep["opt_bytes"]
        assert 0.5 <= ratio < 0.6, (
            f"stage {stage}: per-device opt bytes ratio {ratio:.3f} "
            f"not ~1/2 ({rep})")
        print(f"   dp=2 stage {stage}: loss parity ok, opt bytes "
              f"{rep['opt_bytes']} -> {rep['opt_bytes_per_device']} "
              f"per device (x{ratio:.3f})")
    return 0


def run_main() -> int:
    import numpy as np

    print("zero_smoke: 1x1 ZeRO parity (stages 0/1/2)")
    for stage in (0, 1, 2):
        _parity((1, 1), stage)
    print("   1x1: all stages match the unsharded baseline")

    print("zero_smoke: dp=2 subprocess (2 virtual CPU devices)")
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=2").strip()
    env["JAX_PLATFORMS"] = "cpu"
    subprocess.run([sys.executable, os.path.abspath(__file__), "--dp2"],
                   env=env, check=True)

    print("zero_smoke: publish -> hot-swap -> serve")
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed import zero
    from paddle_tpu.incubate.checkpoint import CheckpointSaver
    from paddle_tpu.models.generation import greedy_search
    from paddle_tpu.serving import ServingEngine

    t_model, t_opt = _build(seed=11)
    step = zero.zero_train_step(
        _train_fn(t_model, t_opt), layers=[t_model], optimizers=[t_opt],
        mesh=_mesh((1, 1)), stage=1)
    for ids, labels in _data():
        step(ids, labels)
    tmp = tempfile.mkdtemp(prefix="zero_smoke_")
    saver = CheckpointSaver(tmp, "publish")
    zero.save_train_state(saver, [t_model], [t_opt], 0)
    state, meta = saver.load()
    assert meta.get("zero_stage") is not None, meta

    s_model, _ = _build(seed=3)
    s_model.eval()
    t_model.eval()
    eng = ServingEngine(s_model, max_slots=2, max_len=32,
                        buckets=[8, 16], max_queue=8)
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, CFG["vocab_size"], size=n).tolist()
               for n in (5, 9)]
    for p in prompts:
        eng.submit(p, max_new_tokens=4)
    eng.run_until_idle()

    before = sum(e["count"] for e in obs.compiles().values())
    version = eng.swap_weights(zero.weights_from_checkpoint(state))
    reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
    eng.run_until_idle()
    after = sum(e["count"] for e in obs.compiles().values())
    assert after == before, (
        f"hot swap cost {after - before} compiles (must be 0)")
    for p, r in zip(prompts, reqs):
        ref = greedy_search(t_model, np.asarray([p]), max_new_tokens=4,
                            cache_len=32)[0].tolist()
        assert r.output_ids == ref, "post-swap tokens != trained greedy"
    print(f"   swap v{version}: 0 new compiles, tokens match the "
          f"trained model")
    print("ZERO SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(run_dp2() if "--dp2" in sys.argv[1:] else run_main())

#!/usr/bin/env bash
# CI harness — the build-tooling tier (SURVEY §2.8; analog of the
# reference's paddle_build.sh + CI scripts, scoped to what matters for a
# pure-python+native-extension tree):
#
#   1. import smoke (the package must import with no toolchain at all)
#   2. lint: static program verifier + shape/dtype inference over the
#      eight book programs + op-registry grad-contract diff vs baseline
#   3. sharding-rule lint (GSPMD pre-flight: dead/shadowed rules,
#      divisibility fallbacks, per-device memory estimate)
#   4. serving concurrency/lifecycle lint (AST dataflow over the
#      serving modules: KV/LoRA resources released on every path incl.
#      exception edges, no double-release or release-after-move, and
#      every write to `# guarded-by` state under its declared lock —
#      strict, with an empty justified baseline)
#   5. full test suite on the virtual 8-device CPU mesh
#   6. chaos suite (deterministic fault injection: retry/skip/rollback
#      recovery paths under FLAGS_fault_spec-driven failures)
#   7. serving plane (continuous-batching engine == sequential decode
#      over the paged KV cache — block tables, prefix reuse and COW
#      token-identical with AND without the prefix cache, compile-count
#      budget re-asserted on the paged step names, queue backpressure,
#      block-pool exhaustion head-of-line; reduced in quick mode) plus
#      the fused-attention oracle: the Pallas paged decode kernel with
#      the int8 KV pool (FLAGS_serving_attn_impl=pallas +
#      FLAGS_serving_kv_dtype=int8, interpret mode on CPU) must stay
#      token-identical to the XLA/f32 engine and sequential greedy;
#      plus the mesh-serving gate: tensor-parallel pjit steps
#      (FLAGS_serving_mesh) and the data-parallel ReplicaRouter
#      (FLAGS_serving_replicas) token-identical to greedy with the
#      step-compile budget shared across replicas; plus the
#      disaggregated-serving gate: a prefill/decode role-split fleet
#      (FLAGS_serving_disagg, KV block handoff + prefix-affinity
#      routing) token-identical to the symmetric router at zero extra
#      compiles, with the chaos kill-prefill-worker path leaking
#      nothing
#   8. speculative-decoding gate (FLAGS_serving_spec_tokens>0 engine
#      token-identical to sequential greedy, compile counts pinned;
#      full mode also runs the BENCH_MODEL=serving spec variant on a
#      tiny model: tokens/s + acceptance rate vs the plain engine)
#   9. observability gate (train + serving smoke under the run log;
#      /metrics parses as Prometheus text, compile tracker pins the
#      decode/prefill compile budget, run-log events feed
#      tools/trace_summary.py; per-request tracing blame identity +
#      Perfetto export + /v1/requests/<id> debug endpoint, with the
#      recompile predictor proving tracing never compiles; plus the
#      host-KV-tier session phase: a two-turn session demoted to
#      host RAM and resumed token-identically, migration/session
#      metrics and run-log events minted, predictor agreeing
#      host_tier/sessions are validated no-ops)
#  10. loadgen SLO gate (seeded open-loop traffic through the
#      SLO-admitting gpt2-tiny engine: goodput > 0 with attainment
#      reported and zero leaked KV blocks, then the chaos crossover —
#      submit/alloc faults injected, degradation must stay graceful —
#      then the same traffic through a --disagg 1x2 fleet: goodput
#      still > 0, handoffs actually happened, still zero leaks —
#      closing with the tracing-overhead budget: a fully-traced run
#      must hold goodput within 5% of an untraced one on the same
#      seed — and the hedging-under-chaos crossover: closed-loop
#      traffic with a deterministic straggler replica, a mid-run
#      chaos kill and 10% client abandonment (disconnect -> cancel
#      with full reclaim), where the hedged arm must beat the
#      unhedged arm's goodput at zero leaks / zero new compiles —
#      and the returning-users host-tier gate: seeded multi-turn
#      session traffic that parks MORE concurrent sessions than the
#      device pool has KV blocks (idle chains demoted to the pinned
#      host pool, promoted back token-identically on resume), at
#      zero leaks in both tiers and zero new compiles after warmup) —
#      and the device-cost observatory: FLAGS_serving_devprof at the
#      default 10% sampling must hold goodput within 2% of a
#      devprof-off run on the same seed, and a seeded virtual-clock
#      run appends a tools/perf_ledger.py row that must pass
#      tools/perf_regress.py against the committed
#      tools/perf_baseline.json (the perf-regression trajectory gate)
#  11. chaos soak gate (hours of seeded diurnal traffic on the virtual
#      clock with replica kills injected at virtual instants and
#      auto-restart healing the fleet: goodput > 0 in every window,
#      completed + rehomed + shed == offered, zero leaks, zero new
#      compiles after warmup — kill/restart/re-home proven no-ops),
#      then the same seeded soak under FLAGS_sanitize_locks=1 (lock
#      order graph acyclic, zero guarded-state violations)
#  12. op coverage gate (>= 80% of the reference forward-op surface)
#  13. API-freeze check (public signature snapshot diff)
#  14. multi-chip dry-run (GSPMD train step on N virtual devices)
#  15. train->serve loop gate (ZeRO parity on 1x1 + virtual dp=2 with
#      per-device optimizer bytes ~1/dp, then checkpoint publish ->
#      live hot-swap into a running engine with zero new compiles)
#  16. README generated fragments vs their registries (no drift)
#
# Usage: tools/ci.sh [quick]   — `quick` skips the full suite and runs
# a reduced chaos subset; lint and the other static gates still run

set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1/16 import smoke"
JAX_PLATFORMS=cpu python -c "
import paddle_tpu
from paddle_tpu.ops import registry
n = len(registry.registered_ops())
assert n > 350, n
print(f'   paddle_tpu imports, {n} op lowerings registered')
"

echo "== 2/16 lint (program verifier + shape inference + op-desc compat)"
JAX_PLATFORMS=cpu python tools/lint_program.py --books --shapes
JAX_PLATFORMS=cpu python tools/check_op_desc.py --diff tools/op_desc_baseline.json

echo "== 3/16 sharding-rule lint (GSPMD pre-flight)"
# the GPT TP table, the ZeRO-style fully-sharded merge, and the serving
# TP table (the mesh-sharded engine's placement rules on its
# ("data","model") mesh) against the GPT benchmark model: no unknown
# axes (ERROR), zero dead/shadowed rules since the encoder rules split
# into their own table, and — now that the CI model pads its vocab to a
# mesh-divisible 98 rows (GPTConfig.vocab_pad_to) — zero warnings
# either, so the gate runs --strict; the gpt_tp run also prints the
# static ZeRO-1 per-device optimizer-byte estimate
JAX_PLATFORMS=cpu python tools/lint_sharding.py --preset gpt_tp --mesh dp=2,mp=2 --strict --zero-stage 1
JAX_PLATFORMS=cpu python tools/lint_sharding.py --preset serving_tp --mesh data=1,model=2 --strict
JAX_PLATFORMS=cpu python tools/lint_sharding.py --preset gpt_tp+fully_sharded --mesh dp=2,mp=2 --json > /dev/null

echo "== 4/16 serving concurrency/lifecycle lint"
# static resource-obligation dataflow (acquire/release/export/adopt)
# plus guarded-state discipline over the serving modules; --strict
# fails on warnings too, and the baseline ships empty — every real
# finding gets fixed, not suppressed
JAX_PLATFORMS=cpu python tools/lint_serving.py --strict

if [[ "${1:-}" != "quick" ]]; then
  echo "== 5/16 test suite (virtual 8-device CPU mesh)"
  if python -c 'import pytest_timeout' 2>/dev/null; then
    python -m pytest tests/ -q -x --timeout=1200
  else
    python -m pytest tests/ -q -x
  fi
else
  echo "== 5/16 test suite: SKIPPED (quick mode)"
fi

if [[ "${1:-}" != "quick" ]]; then
  echo "== 6/16 chaos suite (deterministic fault injection)"
  python -m pytest tests/ -q -m chaos
else
  echo "== 6/16 chaos suite: reduced subset (quick mode)"
  python -m pytest tests/test_resilience.py -q
fi

if [[ "${1:-}" != "quick" ]]; then
  echo "== 7/16 serving plane (incl. paged-KV equivalence)"
  # the full file carries the paged oracle: engine output token-identical
  # to sequential greedy with the prefix cache on AND off, plus the
  # dense paged=False baseline and the paged compile-count pins
  JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q
  echo "   fused paged kernel + int8 KV oracle (Pallas interpret mode)"
  JAX_PLATFORMS=cpu python -m pytest tests/test_paged_attention.py -q
  echo "   mesh-sharded serving gate (pjit steps + replica router)"
  # tensor-parallel engine token-identical to greedy on the 1x1 mesh
  # AND on a real (1,2) head-split over the virtual devices; N router
  # replicas share one model and compile each step exactly once
  python -m pytest tests/test_serving_mesh.py tests/test_serving_router.py -q
  echo "   disaggregated prefill/decode gate (handoff + prefix affinity)"
  # role-split fleet token-identical to the symmetric ReplicaRouter at
  # zero extra compiles; affinity routing beats least-loaded on shared
  # prefixes; killing a prefill worker mid-handoff leaks nothing
  JAX_PLATFORMS=cpu python -m pytest tests/test_serving_disagg.py -q
  echo "   host KV tier gate (session park/resume + fleet dedup)"
  # sessions demoted to the pinned host pool resume token-identically
  # (incl. spec K=2, int8 KV, LoRA pins), promotion is all-or-nothing,
  # one fleet-shared store dedups chains across workers, and chaos at
  # serving.replica + serving.migrate leaks zero blocks on either tier
  JAX_PLATFORMS=cpu python -m pytest tests/test_kv_tier.py -q
else
  echo "== 7/16 serving plane: reduced subset (quick mode)"
  JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q \
    -k "matches_sequential or queue_full or slot_kv or block_allocator \
or paged_engine_matches or dense_engine_still or prefix_reuse"
  JAX_PLATFORMS=cpu python -m pytest tests/test_paged_attention.py -q \
    -k "engine_pallas_matches or kernel_matches_reference_int8"
  echo "   mesh-sharded serving gate: reduced subset (quick mode)"
  python -m pytest tests/test_serving_mesh.py tests/test_serving_router.py \
    -q -m "not slow" \
    -k "matches_sequential_greedy or unified_cache or share_compiled \
or head_sharded or drain or chaos_skip"
  echo "   disaggregated prefill/decode gate: reduced subset (quick mode)"
  JAX_PLATFORMS=cpu python -m pytest tests/test_serving_disagg.py \
    -q -m "not slow" \
    -k "matches_symmetric or zero_compiles or backpressure \
or flag_parsing"
  echo "   host KV tier gate: reduced subset (quick mode)"
  JAX_PLATFORMS=cpu python -m pytest tests/test_kv_tier.py -q \
    -k "(resumes_token_identical and greedy) or fleet_dedup \
or all_or_nothing or evicts_lru or session_store"
fi

echo "== 8/16 speculative decoding gate"
JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q -k "spec"
if [[ "${1:-}" != "quick" ]]; then
  echo "   bench: spec vs non-spec on the repetitive-suffix workload"
  BENCH_MODEL=serving BENCH_SERVING_GPT=gpt2-tiny BENCH_BATCH=4 \
    BENCH_SEQ=64 BENCH_STEPS=1 BENCH_SERVING_NEW_TOKENS=16 \
    BENCH_SERVING_COMPARE=0 JAX_PLATFORMS=cpu python bench.py
fi

echo "== 9/16 observability gate"
# tiny train + serving smoke under the run log: /metrics parses as
# Prometheus text (incl. KV block-pool gauges), compile tracker pins
# decode_step_paged==1 compile and one batched prefill dispatch, a
# repeated prompt scores a prefix-cache hit, JSONL events feed
# trace_summary
JAX_PLATFORMS=cpu python tools/obs_smoke.py

echo "== 10/16 loadgen SLO gate (goodput under real traffic)"
# seeded open-loop traffic through the gpt2-tiny engine with SLO-aware
# admission: goodput > 0 with attainment reported, zero leaked KV
# blocks, zero unhandled exceptions — then the chaos crossover: the
# same workload with submit/alloc faults injected must degrade
# gracefully (goodput still > 0, every loss accounted as a shed,
# still zero leaks)
if [[ "${1:-}" != "quick" ]]; then
  LG_DURATION=2; LG_RATE=20
else
  LG_DURATION=1; LG_RATE=12
fi
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode bursty --rate "$LG_RATE" --duration "$LG_DURATION" --seed 0 \
  --slots 4 --max-len 64 --buckets 16,32 --prompt-tokens 4:16 \
  --new-tokens 2:8 --priority-mix 0:0.2,1:0.6,2:0.2 \
  --slo-ttft-ms 2000 --json \
  --expect-goodput-min 0.5 --expect-zero-leaks \
  | JAX_PLATFORMS=cpu python -c "
import json, sys
r = json.loads(sys.stdin.read())
assert r['slo_attainment'] is not None, r
assert r['exceptions'] == 0, r
print(f\"   clean: goodput {r['goodput_per_s']}/s, \"
      f\"attainment {r['slo_attainment']}\")
"
echo "   chaos crossover (serving.submit + serving.alloc faults)"
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode bursty --rate "$LG_RATE" --duration "$LG_DURATION" --seed 0 \
  --slots 4 --max-len 64 --buckets 16,32 --prompt-tokens 4:16 \
  --new-tokens 2:8 --priority-mix 0:0.2,1:0.6,2:0.2 \
  --slo-ttft-ms 2000 --json \
  --fault-spec "serving.submit:skip@0.2;serving.alloc:skip@0.2" \
  --expect-goodput-min 0.1 --expect-zero-leaks --expect-sheds-min 1 \
  | JAX_PLATFORMS=cpu python -c "
import json, sys
r = json.loads(sys.stdin.read())
assert r['exceptions'] == 0, r
assert r['shed'].get('fault', 0) >= 1, r
print(f\"   chaos: goodput {r['goodput_per_s']}/s, \"
      f\"{r['shed_total']} shed ({r['shed']}), 0 leaks\")
"
echo "   disagg fleet (1 prefill x 2 decode, prefix-affinity routing)"
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode bursty --rate "$LG_RATE" --duration "$LG_DURATION" --seed 0 \
  --slots 4 --max-len 64 --buckets 16,32 --prompt-tokens 4:16 \
  --new-tokens 2:8 --slo-ttft-ms 2000 --disagg 1x2 --json \
  --expect-goodput-min 0.5 --expect-zero-leaks \
  | JAX_PLATFORMS=cpu python -c "
import json, sys
r = json.loads(sys.stdin.read())
assert r['exceptions'] == 0, r
d = r['disagg']
assert d['prefill_workers'] == 1 and d['decode_workers'] == 2, d
assert d['handoffs_adopted'] >= 1, d
print(f\"   disagg: goodput {r['goodput_per_s']}/s, \"
      f\"{d['handoffs_adopted']} handoffs \"
      f\"({d['affinity_hits']} affinity hits), 0 leaks\")
"
echo "   multi-tenant decode mix (2 LoRA tenants + sampled rows)"
# seeded burst mixing greedy/sampled rows across three tenants on one
# compiled engine: per-tenant goodput reported, zero leaked KV blocks
# or adapter pages, and — the sampling-as-data / paged-LoRA contract —
# zero new XLA compiles after warmup
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode bursty --rate "$LG_RATE" --duration "$LG_DURATION" --seed 0 \
  --slots 4 --max-len 64 --buckets 16,32 --prompt-tokens 4:16 \
  --new-tokens 2:8 --slo-ttft-ms 2000 --json \
  --sample-frac 0.5 --tenant-mix base:0.5,acme:0.3,zeta:0.2 \
  --lora-rank 2 \
  --expect-goodput-min 0.1 --expect-zero-leaks \
  --expect-zero-new-compiles \
  | JAX_PLATFORMS=cpu python -c "
import json, sys
r = json.loads(sys.stdin.read())
assert r['exceptions'] == 0, r
pt = r['per_tenant']
assert set(pt) == {'base', 'acme', 'zeta'}, pt
assert sum(t['completed'] for t in pt.values()) == r['completed'], pt
assert any(t['sampled'] for t in pt.values()), pt
assert r['leaked_lora_pages'] == 0, r
assert r['new_compiles_after_warmup'] == 0, r
print(f\"   tenants: \" + \", \".join(
    f\"{n} {t['completed']}/{t['offered']}\" for n, t in pt.items())
      + f\", 0 new compiles, 0 leaks\")
"
echo "   tracing-overhead budget (traced vs untraced, <= 5%)"
# per-request tracing is pure host-side mark appends on the engine
# clock (never a jit input), so a fully-traced run must hold goodput
# within 5% of an untraced one on the same seed — the workload is
# step-compute dominated, which keeps the wall-clock ratio stable
TRACED_JSON=$(mktemp); UNTRACED_JSON=$(mktemp)
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode bursty --rate "$LG_RATE" --duration "$LG_DURATION" --seed 0 \
  --slots 4 --max-len 64 --buckets 16,32 --prompt-tokens 4:16 \
  --new-tokens 2:8 --slo-ttft-ms 2000 --trace-sample 1.0 --json \
  --expect-zero-leaks > "$TRACED_JSON"
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode bursty --rate "$LG_RATE" --duration "$LG_DURATION" --seed 0 \
  --slots 4 --max-len 64 --buckets 16,32 --prompt-tokens 4:16 \
  --new-tokens 2:8 --slo-ttft-ms 2000 --trace-sample 0.0 --json \
  --expect-zero-leaks > "$UNTRACED_JSON"
JAX_PLATFORMS=cpu python - "$TRACED_JSON" "$UNTRACED_JSON" <<'PY'
import json, sys
t = json.load(open(sys.argv[1]))
u = json.load(open(sys.argv[2]))
assert t["completed"] == u["completed"], (t["completed"], u["completed"])
assert t["blame"]["requests"] > 0, t.get("blame")
gt, gu = t["goodput_per_s"], u["goodput_per_s"]
drop = (gu - gt) / gu if gu else 0.0
assert drop <= 0.05, \
    f"tracing overhead {drop:.1%} > 5% budget ({gt} vs {gu}/s)"
print(f"   tracing overhead: traced {gt}/s vs untraced {gu}/s "
      f"({drop:+.1%} of the 5% budget)")
PY
rm -f "$TRACED_JSON" "$UNTRACED_JSON"
echo "   hedging under chaos (straggler + kill + 10% abandonment)"
# the request-lifecycle robustness crossover: seeded closed-loop
# traffic against a 2-replica fleet where replica 0 is a deterministic
# straggler (slow-but-alive, below the strikes watchdog), a chaos kill
# removes it mid-run, and 10% of clients disconnect mid-decode
# (--abandon-frac -> cancel with full reclaim). The hedged arm
# (--hedge-ms) must fire at least one hedge and beat the unhedged
# arm's goodput under the identical fault schedule; both arms must
# account every request (completed + canceled == admitted offered),
# leak zero KV blocks, and compile nothing new after warmup. The
# seed/rate pair is load-bearing: seed 3 at rate 20 x 2s is a schedule
# whose abandonment stream actually selects clients.
HEDGED_JSON=$(mktemp); UNHEDGED_JSON=$(mktemp)
HEDGE_ARGS=(--model gpt2-tiny --mode poisson --rate 20 --duration 2
  --seed 3 --slots 4 --max-len 64 --buckets 16,32 --prompt-tokens 4:16
  --new-tokens 2:8 --replicas 2 --depth-only --slo-ttft-ms 400
  --closed-loop 4 --think-time-ms 0:20 --abandon-frac 0.1
  --straggler 0:600 --chaos 2.5:kill:0 --json
  --expect-zero-leaks --expect-zero-new-compiles)
JAX_PLATFORMS=cpu python tools/loadgen.py "${HEDGE_ARGS[@]}" \
  > "$UNHEDGED_JSON"
JAX_PLATFORMS=cpu python tools/loadgen.py "${HEDGE_ARGS[@]}" \
  --hedge-ms 100 --hedge-budget 0.3 > "$HEDGED_JSON"
JAX_PLATFORMS=cpu python - "$HEDGED_JSON" "$UNHEDGED_JSON" <<'PY'
import json, sys
h = json.load(open(sys.argv[1]))
u = json.load(open(sys.argv[2]))
for arm in (h, u):
    assert arm["exceptions"] == 0, arm
    assert arm["chaos_applied"] == 1, arm
    assert arm["abandoned"] >= 1, arm
    assert arm["canceled"].get("disconnect", 0) == arm["abandoned"], arm
    assert arm["leaked_kv_blocks"] == 0, arm
    assert arm["new_compiles_after_warmup"] == 0, arm
# identical seed -> identical abandonment in both arms
assert h["abandoned"] == u["abandoned"], (h["abandoned"], u["abandoned"])
hs = h["hedges"]
assert hs["fired"] >= 1, hs
assert hs["pending"] == 0, hs
gh, gu = h["goodput_per_s"], u["goodput_per_s"]
assert gh > gu, f"hedged goodput {gh}/s not above unhedged {gu}/s"
print(f"   hedging: goodput {gh}/s vs {gu}/s unhedged, "
      f"{hs['fired']} fired / {hs['wins']} won, "
      f"{h['abandoned']} abandoned -> canceled, 0 leaks, 0 new compiles")
PY
rm -f "$HEDGED_JSON" "$UNHEDGED_JSON"
echo "   returning users (host KV tier: park sessions > device blocks)"
# the million-session contract: seeded multi-turn session traffic on
# the virtual clock where each returning user's idle gap demotes their
# KV chain to the pinned host pool (serving.migrate is fault-eligible)
# and the next turn promotes it back token-identically. The run must
# park strictly more concurrent sessions than the device pool has KV
# blocks (the capacity headroom comes from host RAM, not HBM), resume
# at least one session, leak zero blocks in BOTH tiers, and compile
# nothing new after warmup — migrations are host-side numpy surgery,
# never a jit input. The trace replays byte-identically from seed 3.
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode poisson --rate "$LG_RATE" --duration "$LG_DURATION" --seed 3 \
  --slots 1 --max-len 64 --buckets 8,16,32 --prompt-tokens 4:8 \
  --new-tokens 2:4 --returning-frac 0.9 --turns-per-session 2:3 \
  --host-blocks 64 --demote-idle-ms 0 --virtual-step-ms 5 --json \
  --expect-resumed-min 1 --expect-zero-leaks \
  --expect-zero-new-compiles --expect-capacity-gt-device \
  | JAX_PLATFORMS=cpu python -c "
import json, sys
r = json.loads(sys.stdin.read())
assert r['exceptions'] == 0, r
s = r['sessions']
assert s['sessions_resumed'] >= 1, s
assert s['sessions_peak'] > s['device_blocks'], s
assert s['leaked_host_blocks'] == 0 and r['leaked_kv_blocks'] == 0, r
assert r['new_compiles_after_warmup'] == 0, r
assert s['migrated_demote_blocks'] >= s['migrated_promote_blocks'] >= 1, s
print(f\"   sessions: {s['sessions_peak']} peak on \"
      f\"{s['device_blocks']} device blocks, \"
      f\"{s['sessions_resumed']} resumed, \"
      f\"{s['migrated_demote_blocks']}/{s['migrated_promote_blocks']} \"
      f\"blocks demoted/promoted, 0 leaks both tiers, 0 new compiles\")
"
echo "   devprof overhead budget (observatory on vs off, <= 2%)"
# the device-cost observatory at the default 10% sampling pays one
# block_until_ready per sampled dispatch and captures each compile's
# cost analysis out-of-band — a fully-armed run must hold goodput
# within 2% of a devprof-off run on the same seed (measured headroom
# is ~0.05%; the budget is the contract, not the expectation)
DEVPROF_JSON=$(mktemp); NODEVPROF_JSON=$(mktemp)
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode bursty --rate "$LG_RATE" --duration "$LG_DURATION" --seed 0 \
  --slots 4 --max-len 64 --buckets 16,32 --prompt-tokens 4:16 \
  --new-tokens 2:8 --slo-ttft-ms 2000 --devprof --json \
  --expect-zero-leaks > "$DEVPROF_JSON"
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode bursty --rate "$LG_RATE" --duration "$LG_DURATION" --seed 0 \
  --slots 4 --max-len 64 --buckets 16,32 --prompt-tokens 4:16 \
  --new-tokens 2:8 --slo-ttft-ms 2000 --json \
  --expect-zero-leaks > "$NODEVPROF_JSON"
JAX_PLATFORMS=cpu python - "$DEVPROF_JSON" "$NODEVPROF_JSON" <<'PY'
import json, sys
d = json.load(open(sys.argv[1]))
p = json.load(open(sys.argv[2]))
assert d["completed"] == p["completed"], (d["completed"], p["completed"])
dp = d["devprof"]
assert dp["dispatches"] > 0 and dp["samples"] >= 1, dp
gd, gp = d["goodput_per_s"], p["goodput_per_s"]
drop = (gp - gd) / gp if gp else 0.0
assert drop <= 0.02, \
    f"devprof overhead {drop:.1%} > 2% budget ({gd} vs {gp}/s)"
print(f"   devprof overhead: armed {gd}/s vs off {gp}/s "
      f"({drop:+.1%} of the 2% budget, "
      f"{dp['samples']}/{dp['dispatches']} dispatches sampled)")
PY
rm -f "$DEVPROF_JSON" "$NODEVPROF_JSON"
echo "   perf-regression ledger (seeded row vs committed baseline)"
# the same seeded virtual-clock scenario that produced the committed
# tools/perf_baseline.json: wall time never leaks in, so the gated
# metrics (goodput / TTFT p95 / TPOT p95) reproduce exactly and the
# 10% default tolerance only absorbs intentional schema drift. A real
# perf change fails here and is reviewed by regenerating the baseline
# (tools/perf_regress.py --write-baseline) and committing the diff.
PERF_LEDGER=$(mktemp)
JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
  --mode poisson --rate 30 --duration 0.5 --seed 3 \
  --slots 4 --max-len 128 --buckets 16,32,64 --prompt-tokens 4:24 \
  --new-tokens 2:16 --virtual-step-ms 4 --slo-ttft-ms 60 \
  --devprof --devprof-sample 1.0 --ledger "$PERF_LEDGER" --json \
  --expect-zero-leaks > /dev/null
JAX_PLATFORMS=cpu python tools/perf_regress.py "$PERF_LEDGER" \
  --baseline tools/perf_baseline.json | sed 's/^/   /'
rm -f "$PERF_LEDGER"

echo "== 11/16 chaos soak gate (virtual-clock fleet fault tolerance)"
# hours of seeded diurnal traffic compressed into seconds on the
# virtual clock, with replica kills injected at virtual instants
# (serving.replica:error@t>Ns, one FLAGS_fault_spec string — the
# schedule replays byte-identically from the seed) and auto-restart
# healing the fleet: goodput > 0 in every traffic window that offered
# load, completed + rehomed + shed == offered, zero leaked KV blocks,
# zero unhandled exceptions, zero new compiles after warmup — and the
# recompile predictor proving kill/restart/re-home add none; the
# extended accounting identity (completed + rehomed + shed + canceled
# == offered) and the hedge-budget envelope are re-asserted on a
# closed-loop arm with client abandonment below
if [[ "${1:-}" != "quick" ]]; then SOAK_HOURS=2; else SOAK_HOURS=1; fi
JAX_PLATFORMS=cpu python tools/soak.py --model gpt2-tiny \
  --hours "$SOAK_HOURS" --rate 0.02 --kills 2 --replicas 2 --seed 0 \
  --windows 8 --json \
  --expect-kills-min 2 --expect-goodput-every-window \
  --expect-zero-leaks --expect-zero-new-compiles --expect-identity \
  | JAX_PLATFORMS=cpu python -c "
import json, sys
r = json.loads(sys.stdin.read())
rep = r['report']
assert rep['kills'] >= 2 and rep['restarts'] >= 2, rep
assert r['identity_ok'] and r['predictor_noop'], r
print(f\"   soak: {r['simulated_hours']}h simulated, \"
      f\"{rep['kills']} kills/{rep['restarts']} restarts, \"
      f\"{rep['rehomed']} re-homed, goodput {rep['goodput_per_s']}/s, \"
      f\"0 leaks, 0 new compiles\")
"
# closed-loop soak with 15% client abandonment and hedging armed:
# every disconnect must land as a cancel with full reclaim, the
# extended accounting identity must hold (completed + rehomed + shed
# + canceled == offered — --expect-identity covers the canceled
# term), and hedge volume must stay inside the token-bucket envelope
# (--expect-hedge-budget-respected: fired <= 1 + budget * offered)
JAX_PLATFORMS=cpu python tools/soak.py --model gpt2-tiny \
  --hours 0.5 --rate 0.02 --kills 0 --replicas 2 --seed 3 \
  --windows 4 --closed-loop 4 --abandon-frac 0.15 \
  --hedge-ms 50 --hedge-budget 0.3 --json \
  --expect-zero-leaks --expect-zero-new-compiles \
  --expect-identity --expect-hedge-budget-respected \
  | JAX_PLATFORMS=cpu python -c "
import json, sys
r = json.loads(sys.stdin.read())
rep = r['report']
assert r['identity_ok'] and r['predictor_noop'], r
assert r['hedge_budget_ok'], r
assert rep['abandoned'] >= 1, rep
assert rep['canceled'].get('disconnect', 0) == rep['abandoned'], rep
print(f\"   abandonment soak: {rep['abandoned']} disconnects -> \"
      f\"cancels, identity holds with canceled term, \"
      f\"{rep['hedges']['fired']} hedges inside budget, 0 leaks\")
"
# the same seeded soak under the runtime concurrency sanitizer
# (FLAGS_sanitize_locks=1): every make_lock() lock instrumented, the
# acquisition-order graph must stay acyclic and every guarded-state
# write must happen under its declared lock, through kills, restarts
# and re-homes — a shorter soak, since the schedule is the same
FLAGS_sanitize_locks=1 JAX_PLATFORMS=cpu python tools/soak.py \
  --model gpt2-tiny --hours 0.5 --rate 0.02 --kills 1 --replicas 2 \
  --seed 0 --windows 4 --json \
  --expect-kills-min 1 --expect-zero-leaks --expect-zero-new-compiles \
  --expect-identity --expect-sanitizer-clean \
  | JAX_PLATFORMS=cpu python -c "
import json, sys
r = json.loads(sys.stdin.read())
san = r['sanitizer']
assert san['enabled'] and san['lock_acquires'] > 0, san
assert not san['cycles'] and not san['violations'], san
print(f\"   sanitized soak: {san['lock_acquires']} acquires over \"
      f\"{san['locks_tracked']} locks, {san['order_edges']} order \"
      f\"edges, 0 cycles, 0 violations\")
"

echo "== 12/16 op coverage gate"
if [[ -d /root/reference ]]; then
  JAX_PLATFORMS=cpu python tools/op_coverage.py --json
else
  echo "   reference tree absent — skipped"
fi

echo "== 13/16 API freeze"
SNAP=tools/api_signatures.txt
API_NOW=$(mktemp)
API_DIFF=$(mktemp)
trap 'rm -f "$API_NOW" "$API_DIFF"' EXIT
JAX_PLATFORMS=cpu python tools/print_signatures.py > "$API_NOW"
if [[ -f "$SNAP" ]]; then
  if ! diff -u "$SNAP" "$API_NOW" > "$API_DIFF"; then
    echo "   PUBLIC API CHANGED vs snapshot:"
    head -40 "$API_DIFF"
    echo "   (intentional? refresh with: python tools/print_signatures.py > $SNAP)"
    exit 1
  fi
  echo "   public API matches snapshot ($(wc -l < "$SNAP") symbols)"
else
  cp "$API_NOW" "$SNAP"
  echo "   snapshot created ($(wc -l < "$SNAP") symbols) — commit it"
fi

echo "== 14/16 multi-chip dry run"
# needs the jax_num_cpu_devices config option to carve out virtual CPU
# devices; older jax builds (0.4.x) don't have it
if JAX_PLATFORMS=cpu python -c "
import jax
raise SystemExit(0 if hasattr(jax.config, 'jax_num_cpu_devices') else 1)
" 2>/dev/null; then
  python -c "
import __graft_entry__ as g
g.dryrun_multichip(8)
print('   8-device GSPMD train step ok')
"
else
  echo "   installed jax has no jax_num_cpu_devices — skipped"
fi

echo "== 15/16 train->serve loop gate (ZeRO + live hot-swap)"
# 2-step ZeRO train runs match the unsharded baseline loss-for-loss on
# a 1x1 mesh and again on a subprocess-carved dp=2 mesh (per-device
# optimizer bytes asserted ~1/2 of total from live shards), then the
# trained weights publish through CheckpointSaver and hot-swap into a
# running ServingEngine: tokens match greedy on the trained model,
# zero new compiles
JAX_PLATFORMS=cpu python tools/zero_smoke.py

echo "== 16/16 README generated-fragment sync"
JAX_PLATFORMS=cpu python tools/sync_readme.py --check

echo "CI PASSED"

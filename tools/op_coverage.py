#!/usr/bin/env python
"""Op coverage gate: diff our lowering registry against the reference's
REGISTER_OPERATOR surface (paddle/fluid/operators/*.cc, 630 registrations,
247 distinct forward op types).

Three buckets:
  covered    — a lowering exists under the same name, or under a documented
               alias (v1 <-> v2 renames, redesigns that subsume the op)
  scoped_out — intentionally absent on TPU, with a reason (CUDA/MKLDNN/
               engine-bridge internals, superseded legacy)
  missing    — real gaps

Usage: python tools/op_coverage.py [--ref /root/reference] [--json]
Exits nonzero if coverage (covered / (covered + missing)) < 80%.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# name in reference -> name (or names) that cover it here
ALIASES = {
    "conditional_block": "cond",            # nested-block cond lowering
    "expand": "expand",                     # v1 registered alongside expand_v2
    "beam_search": "models.generation",     # dense beam search redesign
    "gather_tree": "gather_tree",
    "array_to_lod_tensor": "sequence_pad",  # LoD family -> padded+lengths
    "lod_tensor_to_array": "sequence_unpad",
    "lod_reset": "sequence_pad",
    "merge_lod_tensor": "sequence_concat",
    "write_to_array": "framework/control-flow blocks",
    "read_from_array": "framework/control-flow blocks",
    "shrink_rnn_memory": "rnn (lax.scan carries shrink implicitly)",
    "save": "framework_io.save_persistables",
    "load": "framework_io.load_persistables",
    "save_combine": "framework_io.save_inference_model",
    "load_combine": "framework_io.load_inference_model",
    "print": "flags.check_nan_inf / jax.debug.print hook",
    "py_func": "io_callback path (ops/ps_ops.py pattern)",
    "run_program": "jit.to_static traced partial programs",
    "select_input": "cond",
    "select_output": "cond",
    "get_tensor_from_selected_rows": "distributed/ps/sparse_table.py",
    "merge_selected_rows": "distributed/ps/sparse_table.py",
    "coalesce_tensor": "dygraph/parallel.py gradient bucketing",
    "cross_entropy": "cross_entropy",
    "pull_sparse": "distributed_lookup_table",
    "pull_sparse_v2": "distributed_lookup_table",
    "push_sparse": "distributed_lookup_table_grad",
    "push_sparse_v2": "distributed_lookup_table_grad",
    "amp_check_finite_and_scale": "isfinite + GradScaler (amp/auto_cast.py)",
    "assert": "enforce.py typed-error checks",
    "average_accumulates": "optimizer.ModelAverage (in-graph accumulators)",
    "beam_search_decode": "models/generation.py dense beam search",
    "conditional_block_infer": "cond",
    "create_custom_reader": "io/dataloader.py",
    "delete_var": "XLA buffer lifetime (garbage collector collapsed)",
    "feed": "executor feed bindings (framework/executor.py)",
    "fetch": "executor fetch-as-output (framework/executor.py)",
    "get_places": "distributed/env.py device discovery",
    "lod_array_length": "dense lengths tensors (sequence redesign)",
    "lod_rank_table": "dense lengths tensors (sequence redesign)",
    "max_sequence_len": "dense lengths tensors (sequence redesign)",
    "merge_lod_tensor_infer": "sequence_concat",
    "reorder_lod_tensor_by_rank": "argsort + gather on dense batches",
    "split_lod_tensor": "masked select / cond on dense batches",
    "tensor_array_to_tensor": "stack / concat lowerings",
    "recurrent": "rnn op (lax.scan)",
    "rnn_memory_helper": "rnn op (lax.scan carries)",
    "lookup_sparse_table_init": "distributed/ps/sparse_table.py",
    "lookup_sparse_table_read": "distributed/ps/sparse_table.py",
    "lookup_sparse_table_write": "distributed/ps/sparse_table.py",
    "lookup_sparse_table_grad_split": "distributed/ps/sparse_table.py",
    "lookup_table_dequant": "sparse_table + dequantize_abs_max",
    "nccl": "lax collectives over mesh axes (ops/collective_ops.py)",
    "read": "io/device_loader.py double-buffered reader",
    "push_dense": "distributed/ps runtime dense push (ps/runtime.py)",
}

SCOPED_OUT = {
    # CUDA/engine bridges that have no TPU analog by design (SURVEY §2.3/2.4)
    "tensorrt_engine": "TensorRT bridge — XLA is the compiler here",
    "lite_engine": "Paddle-Lite bridge",
    "cudnn_lstm": "cuDNN-specific kernel; rnn op covers LSTM on lax.scan",
    "c_gen_nccl_id": "NCCL bootstrap — GSPMD/jax.distributed replaces it",
    "gen_nccl_id": "NCCL bootstrap",
    "c_comm_init": "NCCL comm init — mesh axes replace rings",
    "c_comm_init_all": "NCCL comm init",
    "listen_and_serv": "legacy gRPC PS — replaced by distributed/ps RPC",
    "send_and_recv": "legacy gRPC PS",
    "recv_save": "legacy gRPC PS",
    "split_byref": "legacy gRPC PS helper",
    "split_ids": "legacy pslib sharding helper (sparse_table shards inside)",
    "merge_ids": "legacy pslib sharding helper",
    "split_selected_rows": "SelectedRows is a host SparseTable here",
    "lookup_sparse_table_merge": "pslib internal",
    "pull_box_sparse": "BoxPS (FPGA box) internal",
    "push_box_sparse": "BoxPS internal",
    "push_box_extended_sparse": "BoxPS internal",
    "pyramid_hash": "pslib internal",
    "filter_by_instag": "pslib instag pipeline",
    "batch_fc": "rank-service CUDA-only op",
    "rank_attention": "rank-service CUDA-only op",
    "bilateral_slice": "CUDA-only HDRNet op",
    "inplace_abn": "in-place activation BN — XLA buffers are immutable; "
                   "batch_norm+activation fuse instead",
    "var_conv_2d": "pyramid-DNN CUDA op",
    "tree_conv": "tree-based CUDA op",
    "fused_embedding_fc_lstm": "x86 fusion kernel",
    "fusion_gru": "x86 fusion kernel (XLA fuses rnn itself)",
    "fusion_lstm": "x86 fusion kernel",
    "fusion_group": "codegen fusion — XLA fusion replaces it",
    "fusion_repeated_fc_relu": "x86 fusion kernel",
    "fusion_seqconv_eltadd_relu": "x86 fusion kernel",
    "fusion_seqexpand_concat_fc": "x86 fusion kernel",
    "fusion_seqpool_concat": "x86 fusion kernel",
    "fusion_squared_mat_sub": "x86 fusion kernel",
    "attention_lstm": "x86 fusion kernel",
    "dequantize": "MKLDNN INT8 pipeline (fake-quant family covers QAT/PTQ)",
    "quantize": "MKLDNN INT8 pipeline",
    "requantize": "MKLDNN INT8 pipeline",
    "conv2d_fusion": "cuDNN fusion kernel — XLA fuses conv+bias+act",
    "conv2d_inception_fusion": "cuDNN fusion kernel",
    "fused_batch_norm_act": "cuDNN fusion kernel — XLA fuses BN+act",
    "fused_fc_elementwise_layernorm": "CUDA fusion kernel",
    "fused_embedding_seq_pool": "x86 fusion kernel",
    "fusion_seqpool_cvm_concat": "x86 fusion kernel",
    "fusion_transpose_flatten_concat": "CUDA fusion kernel",
    "tdm_child": "pslib TDM tree-index internal",
    "tdm_sampler": "pslib TDM tree-index internal",
    "match_matrix_tensor": "pyramid-DNN search op, dropped from paddle 2.x",
    "sequence_topk_avg_pooling": "pyramid-DNN search op, dropped in 2.x",
    "similarity_focus": "caffe-era op, dropped from paddle 2.x API",
    "spp": "caffe-era spatial pyramid pooling, dropped from 2.x API",
    "roi_perspective_transform": "CUDA OCR op, dropped from 2.x API",
    "checkpoint_notify": "legacy gRPC PS control op",
    "fetch_barrier": "legacy gRPC PS control op",
    "send_barrier": "legacy gRPC PS control op",
    "fake_init": "legacy gRPC PS init stub",
    "prefetch": "legacy gRPC PS prefetch op",
    "pull_box_extended_sparse": "BoxPS internal",
    # dynamic-shape two-stage detection machinery: proposal counts are
    # data-dependent; TPU detection recipes keep this stage host-side or
    # use static-anchor single-stage heads (yolo/ssd ops ARE implemented)
    "generate_proposals": "dynamic proposal machinery (host-side on TPU)",
    "generate_proposal_labels": "dynamic proposal machinery",
    "generate_mask_labels": "dynamic proposal machinery",
    "rpn_target_assign": "dynamic proposal machinery",
    "retinanet_target_assign": "dynamic proposal machinery",
    "retinanet_detection_output": "dynamic proposal machinery",
    "distribute_fpn_proposals": "dynamic proposal machinery",
    "collect_fpn_proposals": "dynamic proposal machinery",
    "locality_aware_nms": "dynamic proposal machinery",
    "mine_hard_examples": "dynamic proposal machinery",
    "detection_map": "host-side eval metric over variable detections",
    "deformable_psroi_pooling": "R-FCN head tied to proposal machinery",
    "box_decoder_and_assign": "R-FCN head tied to proposal machinery",
}


def reference_fwd_ops(ref_root):
    pat = re.compile(r"REGISTER_OPERATOR\(\s*([a-z0-9_]+)")
    ops = set()
    opdir = os.path.join(ref_root, "paddle/fluid/operators")
    for dirpath, _, files in os.walk(opdir):
        for f in files:
            if not f.endswith(".cc"):
                continue
            try:
                text = open(os.path.join(dirpath, f)).read()
            except OSError:
                continue
            ops.update(pat.findall(text))
    return sorted(o for o in ops
                  if not o.endswith("_grad") and not o.endswith("_grad2")
                  and not o.endswith("_grad_grad"))


def classify(ref_root):
    import paddle_tpu  # noqa: F401  (populates the registry)
    from paddle_tpu.ops import registry

    reg = set(registry.registered_ops())
    fwd = reference_fwd_ops(ref_root)
    covered, aliased, scoped, missing = [], [], [], []
    for op in fwd:
        if op in reg:
            covered.append(op)
        elif op + "_v2" in reg or op + "2" in reg:
            aliased.append((op, op + ("_v2" if op + "_v2" in reg else "2")))
        elif op in ALIASES:
            aliased.append((op, ALIASES[op]))
        elif op in SCOPED_OUT:
            scoped.append((op, SCOPED_OUT[op]))
        else:
            missing.append(op)
    return {"total_fwd": len(fwd), "covered": covered, "aliased": aliased,
            "scoped_out": scoped, "missing": missing, "registered": len(reg)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    r = classify(args.ref)
    ncov = len(r["covered"]) + len(r["aliased"])
    denom = ncov + len(r["missing"])
    pct = 100.0 * ncov / max(denom, 1)
    if args.json:
        print(json.dumps({
            "total_fwd": r["total_fwd"], "covered": ncov,
            "scoped_out": len(r["scoped_out"]),
            "missing": r["missing"], "coverage_pct": round(pct, 1)}))
    else:
        print(f"reference fwd op types: {r['total_fwd']}")
        print(f"registered lowerings:   {r['registered']}")
        print(f"covered same-name:      {len(r['covered'])}")
        print(f"covered via alias:      {len(r['aliased'])}")
        for op, via in r["aliased"]:
            print(f"    {op:32s} -> {via}")
        print(f"scoped out (reasoned):  {len(r['scoped_out'])}")
        for op, why in r["scoped_out"]:
            print(f"    {op:32s} : {why}")
        print(f"missing:                {len(r['missing'])}")
        for op in r["missing"]:
            print(f"    {op}")
        print(f"\ncoverage (excl. scoped-out): {pct:.1f}%")
    if pct < 80.0:
        sys.exit(1)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Perf-regression gate: latest ledger row vs a committed baseline.

Reads the newest row of a ``tools/perf_ledger.py`` JSONL ledger and
compares it metric-by-metric against a baseline JSON, with a noise
tolerance. Direction-aware: goodput-like metrics (higher is better)
fail when the row drops below ``baseline * (1 - tol)``; latency-like
metrics (lower is better) fail when the row rises above
``baseline * (1 + tol)``. Exit status is the CI contract — 0 on a
clean run, 1 on any regression.

    python tools/perf_regress.py LEDGER.jsonl \\
        --baseline tools/perf_baseline.json

The committed baseline comes from the same seeded VirtualClock loadgen
scenario the CI gate replays, so the gated metrics are deterministic
and the default tolerance only has to absorb schema drift, not timer
noise. Regenerate it after an intentional perf change with
``--write-baseline`` (then commit the diff — that IS the review
artifact for the perf change).

Baseline format::

    {"schema": 1,
     "metrics": {"goodput_per_s": 24.5,
                 "ttft_ms_p95": {"value": 31.0, "tolerance": 0.2}},
     "cost_digest": "0123abcd...",     # or null
     "source": {...}}                  # provenance, not compared

A metric present in the baseline but missing (or null) on the row is
itself a failure — a report that silently stopped carrying a gated
number must not pass. A ``cost_digest`` mismatch prints a warning
(the XLA cost model changed — often intentional) and fails only under
``--strict-digest``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

SCHEMA = 1

#: metrics where bigger numbers are better; everything else gated is
#: treated as latency-like (smaller is better)
HIGHER_BETTER = {"goodput_per_s", "slo_attainment", "completed",
                 "mfu", "offered"}

DEFAULT_TOLERANCE = 0.10


def _spec(v) -> Tuple[Optional[float], Optional[float], float]:
    """Baseline metric entry -> (value, per-metric tolerance or None,
    absolute slack). Accepts a bare number or {"value":,
    "tolerance":, "slack":}; slack widens the bound by an absolute
    amount — the escape hatch for zero-valued baselines, where any
    relative tolerance still collapses to zero."""
    if isinstance(v, dict):
        val = v.get("value")
        tol = v.get("tolerance")
        slack = v.get("slack")
        return (float(val) if isinstance(val, (int, float)) else None,
                float(tol) if isinstance(tol, (int, float)) else None,
                float(slack) if isinstance(slack, (int, float))
                else 0.0)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v), None, 0.0
    return None, None, 0.0


def compare(row: Dict[str, Any], baseline: Dict[str, Any],
            tolerance: float = DEFAULT_TOLERANCE,
            strict_digest: bool = False
            ) -> Tuple[List[str], List[str]]:
    """Returns (failures, notes). Empty failures == gate passes."""
    failures: List[str] = []
    notes: List[str] = []
    metrics = baseline.get("metrics") or {}
    if not isinstance(metrics, dict) or not metrics:
        failures.append("baseline has no metrics to gate on")
        return failures, notes
    for name in sorted(metrics):
        base, tol, slack = _spec(metrics[name])
        if base is None:
            failures.append(f"{name}: malformed baseline entry "
                            f"{metrics[name]!r}")
            continue
        tol = tolerance if tol is None else tol
        got = row.get(name)
        if isinstance(got, bool) or not isinstance(got, (int, float)):
            failures.append(
                f"{name}: baseline {base:g} but the row carries no "
                f"value (got {got!r})")
            continue
        got = float(got)
        if name in HIGHER_BETTER:
            floor = base * (1.0 - tol) - slack
            if got < floor:
                failures.append(
                    f"{name}: {got:g} < {floor:g} "
                    f"(baseline {base:g} - {tol:.0%})")
            else:
                notes.append(f"{name}: {got:g} ok "
                             f"(baseline {base:g}, floor {floor:g})")
        else:
            ceil = base * (1.0 + tol) + slack
            if got > ceil:
                failures.append(
                    f"{name}: {got:g} > {ceil:g} "
                    f"(baseline {base:g} + {tol:.0%})")
            else:
                notes.append(f"{name}: {got:g} ok "
                             f"(baseline {base:g}, ceiling {ceil:g})")
    base_digest = baseline.get("cost_digest")
    row_digest = row.get("cost_digest")
    if base_digest and row_digest and base_digest != row_digest:
        msg = (f"cost_digest changed: {base_digest} -> {row_digest} "
               "(XLA cost table moved — regenerate the baseline if "
               "intentional)")
        (failures if strict_digest else notes).append(
            msg if strict_digest else "WARNING: " + msg)
    return failures, notes


def write_baseline(path: str, row: Dict[str, Any],
                   metrics: Optional[List[str]] = None):
    """Freeze the given row's gated metrics as the new baseline."""
    gate = metrics or ["goodput_per_s", "ttft_ms_p95", "tpot_ms_p95"]
    doc = {
        "schema": SCHEMA,
        "metrics": {},
        "cost_digest": row.get("cost_digest"),
        "source": {k: row.get(k)
                   for k in ("ts", "git_rev", "run", "label")
                   if row.get(k) is not None},
    }
    for name in gate:
        v = row.get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            if name not in HIGHER_BETTER and v == 0:
                # a zero latency baseline makes every relative bound
                # zero-width; give it 1 unit of absolute slack
                doc["metrics"][name] = {"value": v, "slack": 1.0}
            else:
                doc["metrics"][name] = v
    if not doc["metrics"]:
        raise SystemExit(
            f"refusing to write an empty baseline: row has none of "
            f"{gate}")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare the latest perf-ledger row against a "
                    "committed baseline; exit 1 on regression")
    ap.add_argument("ledger", help="JSONL ledger "
                    "(tools/perf_ledger.py output)")
    ap.add_argument("--baseline", default="tools/perf_baseline.json",
                    help="baseline JSON (default "
                         "tools/perf_baseline.json)")
    ap.add_argument("--tolerance", type=float,
                    default=DEFAULT_TOLERANCE,
                    help="relative noise tolerance for metrics "
                         "without a per-metric override "
                         f"(default {DEFAULT_TOLERANCE})")
    ap.add_argument("--strict-digest", action="store_true",
                    help="treat a cost_digest mismatch as a failure, "
                         "not a warning")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze the latest row as the new baseline "
                         "instead of comparing")
    ap.add_argument("--metrics", default="",
                    help="comma list of row keys to gate when "
                         "writing a baseline (default goodput_per_s,"
                         "ttft_ms_p95,tpot_ms_p95)")
    args = ap.parse_args(argv)

    if not (0.0 <= args.tolerance < 1.0):
        ap.error(f"--tolerance must be in [0, 1), got {args.tolerance}")

    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from tools import perf_ledger

    row = perf_ledger.latest(args.ledger)
    if row is None:
        print(f"FAIL: {args.ledger}: empty ledger", file=sys.stderr)
        return 1

    if args.write_baseline:
        gate = [m for m in args.metrics.split(",") if m] or None
        doc = write_baseline(args.baseline, row, gate)
        print(f"wrote {args.baseline}: "
              f"{json.dumps(doc['metrics'], sort_keys=True)}")
        return 0

    try:
        with open(args.baseline, "r", encoding="utf-8") as f:
            baseline = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"FAIL: cannot read baseline {args.baseline}: {e}",
              file=sys.stderr)
        return 1

    failures, notes = compare(row, baseline,
                              tolerance=args.tolerance,
                              strict_digest=args.strict_digest)
    for n in notes:
        print(n)
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        print(f"perf regression vs {args.baseline} "
              f"(row ts={row.get('ts')}, rev={row.get('git_rev')})",
              file=sys.stderr)
        return 1
    print(f"perf gate ok vs {args.baseline} "
          f"(row ts={row.get('ts')}, rev={row.get('git_rev')})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Regenerate README's generated fragments from their sources of truth.

Three rounds in a row the hand-written README headline drifted from the
measured artifact; this makes the artifacts the single source of truth:

    python tools/sync_readme.py          # rewrite generated fragments
    python tools/sync_readme.py --check  # exit 1 on drift (CI gate)

Three fragments are generated, everything else stays hand-written:
  - the GPT flagship headline bullet (from the latest BENCH_r*.json)
  - the "Static program checks" list between the
    `<!-- BEGIN GENERATED: verifier-checks -->` markers (from
    framework/analysis.py:ANALYSIS_CHECKS +
    analysis/lifecycle.py:CHECK_DOCS + the registered flags)
  - the "Fault tolerance" section between the
    `<!-- BEGIN GENERATED: fault-tolerance -->` markers (from
    resilience/injector.py:FAULT_SITES + the registered flags)
  - the "Serving" section between the
    `<!-- BEGIN GENERATED: serving -->` markers (from the registered
    `FLAGS_serving_*` flags + the serving fault sites)
  - the "Train→serve loop" section between the
    `<!-- BEGIN GENERATED: train-serve -->` markers (from the
    registered `FLAGS_zero_*` flags)
  - the "Observability" section between the
    `<!-- BEGIN GENERATED: observability -->` markers (from
    observability.INSTRUMENT_DOCS / EVENT_DOCS + the registered flags)
"""

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def latest_bench():
    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if not paths:
        raise SystemExit("no BENCH_r*.json artifact found")
    # newest artifact that actually carries a perf record — serving/soak
    # records (e.g. the chaos-soak frontier) have neither "parsed" nor
    # "tail" and don't feed the MFU headline
    for path in reversed(paths):
        with open(path) as f:
            data = json.load(f)
        if "parsed" in data or "tail" in data:
            return path, data.get("parsed") or json.loads(
                data["tail"].strip().splitlines()[-1])
    raise SystemExit("no BENCH_r*.json artifact with a perf record found")


_FLAGSHIP_NAMES = {
    "gpt2_345m_mfu": "GPT-2 345M",
    "gpt2-medium_mfu": "GPT-2 345M",
    "gpt2-1p1b_mfu": "GPT-2-class 1.1B (d=128)",
    "gpt2-1p3b_mfu": "GPT-2-class 1.3B (d=128)",
}


def headline(parsed, src):
    toks = parsed.get("tokens_per_sec_per_chip")
    metric = parsed.get("metric")
    name = _FLAGSHIP_NAMES.get(metric, metric or "flagship")
    via = ("the Pallas flash-attention kernels + per-block recompute + "
           "grads-internal trace-once compiled train step"
           if "1p" in (metric or "") else
           "the Pallas flash-attention kernels + trace-once compiled "
           "train step")
    return (
        f"- {name} training at **{parsed['value']:.2f}% MFU** "
        f"(batch {parsed['batch']}, seq {parsed['seq']}, bf16, bf16 AdamW "
        f"moments; {toks / 1000:.1f}k tokens/s/chip) — "
        f"{parsed['vs_baseline']:.2f}x the 40% north-star target — via "
        f"{via}. "
        f"[generated from {os.path.basename(src)}]"
    )


def sync_headline(text, check):
    """Returns (new_text, drift_message_or_None)."""
    src, parsed = latest_bench()
    if parsed.get("metric") not in _FLAGSHIP_NAMES:
        print(f"latest artifact is {parsed.get('metric')}, not a GPT "
              "flagship; headline left alone")
        return text, None
    want = headline(parsed, src)
    # the generated bullet: starts "- GPT-2 345M training" and ends with
    # the "[generated from ...]" stamp (possibly wrapped over lines)
    pat = re.compile(
        r"- GPT[^\n]*training at[^\n]*(?:\n(?!-)[^\n]*)*")
    m = pat.search(text)
    if not m:
        raise SystemExit("README GPT headline bullet not found")
    current = m.group(0)
    # wrap the generated line to the README's 78-col style
    import textwrap
    wrapped = "\n".join(textwrap.wrap(
        want, width=76, initial_indent="", subsequent_indent="  "))
    if current.strip() == wrapped.strip():
        print("README headline in sync")
        return text, None
    if check:
        return text, (
            "README headline DRIFTS from the bench artifact:\n"
            f"  readme: {' '.join(current.split())[:100]}...\n"
            f"  artifact: {' '.join(wrapped.split())[:100]}...")
    print(f"README headline updated from {os.path.basename(src)}")
    return text[:m.start()] + wrapped + text[m.end():], None


_CHECKS_BEGIN = "<!-- BEGIN GENERATED: verifier-checks -->"
_CHECKS_END = "<!-- END GENERATED: verifier-checks -->"
_VERIFIER_FLAGS = ("check_program", "check_ir_passes", "check_shapes")


def render_checks_block():
    """The verifier-check list, from the live check registry + flags."""
    import textwrap
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu import flags
    from paddle_tpu.analysis.lifecycle import CHECK_DOCS
    from paddle_tpu.framework.analysis import ANALYSIS_CHECKS

    def bullet(head, body):
        return "\n".join(textwrap.wrap(
            f"- {head} — {body}", width=76, subsequent_indent="  "))

    lines = ["Checks (`Program.verify(checks=[...])` selects a subset):",
             ""]
    lines += [bullet(f"`{name}`", cd.description)
              for name, cd in ANALYSIS_CHECKS.items()]
    lines += [
        "",
        "Serving concurrency & lifecycle (`analysis.lifecycle`, the",
        "static half of the concurrency plane — the runtime half is the",
        "`FLAGS_sanitize_locks` sanitizer below): an AST dataflow pass",
        "over the serving sources models the KV/LoRA resource APIs as",
        "obligation effects (acquire creates, release discharges,",
        "export_row *moves* ownership into the handoff record, storing/",
        "returning a handle escapes it to the holder's lifecycle) and",
        "interprets each function over a path-merging abstract state",
        "that follows raise edges and except handlers; a companion pass",
        "checks every write to `# guarded-by: <lock>` attributes happens",
        "under `with self.<lock>:` (declarations inherit across",
        "subclasses; `# holds: <lock>` asserts a caller-held lock,",
        "`# unguarded-ok: <reason>` waives one site). Checks:",
        "",
    ]
    lines += [bullet(f"`{name}`", doc)
              for name, doc in CHECK_DOCS.items()]
    lines += ["", "Flags:", ""]
    defs = flags.list_flags()
    for name in _VERIFIER_FLAGS + ("sanitize_locks",):
        d = defs[name]
        lines.append(bullet(
            f"`FLAGS_{name}` (default `{d['default']}`)", d["help"]))
    lines += ["", "Command line:", ""]
    lines.append(bullet(
        "`python tools/lint_program.py --books --shapes [--json]`",
        "the CI sweep: verifier + static shape/dtype inference over the "
        "eight book programs (exit 1 on ERROR diagnostics; `--json` for "
        "structured output)."))
    lines.append(bullet(
        "`python tools/lint_sharding.py --preset gpt_tp --mesh dp=2,mp=2`",
        "GSPMD sharding-rule lint (`distributed.sharding."
        "lint_sharding_rules`): dead rules, shadowed regexes, "
        "`_fit_spec` replicated fallbacks, unknown mesh axes, and the "
        "per-device parameter-memory estimate — no devices needed "
        "(the mesh is plain axis sizes)."))
    lines.append(bullet(
        "`python tools/lint_serving.py --strict [--json]`",
        "the serving concurrency/lifecycle lint over "
        "engine/router/disagg/kv_cache/lora (`analysis.lifecycle."
        "lint_serving`); `--strict` fails on warnings too, and "
        "`--baseline tools/lint_serving_baseline.json` carries "
        "justified findings — every entry needs a one-line "
        "justification, stale entries warn so the baseline only "
        "shrinks (it ships empty)."))
    lines.append(bullet(
        "`FLAGS_sanitize_locks=1 python tools/soak.py ... "
        "--expect-sanitizer-clean`",
        "the runtime half under chaos: every `make_lock()` lock "
        "records held->acquired order edges (cycles = potential "
        "deadlocks, recorded not raised) and `declare_guarded` "
        "attributes raise `GuardedStateError` on writes without the "
        "declared lock; the soak gate requires zero cycles and zero "
        "violations through kills/restarts/re-homes, and "
        "`analysis.sanitizer_report()` feeds the "
        "`sanitizer_lock_acquires` counter."))
    return "\n".join(lines)


def sync_checks_block(text, check):
    """Returns (new_text, drift_message_or_None)."""
    try:
        b = text.index(_CHECKS_BEGIN) + len(_CHECKS_BEGIN)
        e = text.index(_CHECKS_END)
    except ValueError:
        raise SystemExit("README verifier-checks markers not found")
    current = text[b:e].strip("\n")
    want = render_checks_block()
    if current == want:
        print("README verifier-checks block in sync")
        return text, None
    if check:
        return text, ("README verifier-checks block DRIFTS from "
                      "framework/analysis.py — rerun tools/sync_readme.py")
    print("README verifier-checks block regenerated")
    return text[:b] + "\n" + want + "\n" + text[e:], None


_FAULT_BEGIN = "<!-- BEGIN GENERATED: fault-tolerance -->"
_FAULT_END = "<!-- END GENERATED: fault-tolerance -->"
_FAULT_FLAGS = ("fault_spec", "fault_seed", "retry_max_attempts",
                "retry_base_delay", "retry_max_delay", "retry_deadline",
                "retry_budget_ratio", "retry_budget_reserve",
                "guardian_max_skip", "ps_heartbeat_timeout",
                "ps_connect_timeout", "ps_socket_timeout")


def render_fault_block():
    """Fault-injection sites + resilience flags, from the live
    registries (resilience/injector.py and paddle_tpu/flags.py)."""
    import textwrap
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu import flags
    from paddle_tpu.resilience import FAULT_SITE_DOCS

    def bullet(head, body):
        return "\n".join(textwrap.wrap(
            f"- {head} — {body}", width=76, subsequent_indent="  "))

    lines = [
        "A fault spec is a `;`-separated list of `site:kind[@trigger]`",
        "rules (e.g. `ps.rpc.call:drop@0.05;exec.step:nan@17`), installed",
        "via `FLAGS_fault_spec` or `PADDLE_TPU_FAULT_SPEC`; unset means",
        "every `fault_point` is a no-op. Triggers: absent = every call,",
        "`@N` = exactly the N-th call (0-based), `@N+` = from the N-th",
        "on, `@p` (float with a dot) = probability p from a PRNG seeded",
        "by (`FLAGS_fault_seed`, site, rule index) — the same spec +",
        "seed always injects the same faults — and the virtual-time",
        "pair `@t>Ns` / `@t>Ns+`: fire once (or on every call) after N",
        "seconds have elapsed on the injector's clock. The clock",
        "defaults to `time.monotonic`; `resilience.set_time_source` (or",
        "`fault_scope(..., time_source=...)`) points it at a virtual",
        "clock, so a kill schedule like",
        "`serving.replica:error@t>1800s;serving.replica:error@t>3600s`",
        "replays byte-identically inside a simulated soak",
        "(tools/soak.py). Kinds: `drop` (connection",
        "loss), `error` (OSError), `preempt` (SystemExit, the in-process",
        "preemption analog), `kill` (hard `os._exit`), and the",
        "caller-interpreted `nan` / `corrupt` / `skip`.",
        "",
        "Injection sites:",
        "",
    ]
    lines += [bullet(f"`{site}`", doc)
              for site, doc in FAULT_SITE_DOCS.items()]
    lines += [
        "",
        "Every injected fault counts `STAT_fault_<site>`, every retry",
        "`STAT_retry_<site>`, and every guardian recovery a",
        "`STAT_guardian_*` counter (`paddle_tpu.monitor`), so the chaos",
        "suite (`pytest -m chaos`, tools/ci.sh step 4) asserts recovery",
        "was observed, not just survived.",
        "",
        "Flags:",
        "",
    ]
    defs = flags.list_flags()
    for name in _FAULT_FLAGS:
        d = defs[name]
        lines.append(bullet(
            f"`FLAGS_{name}` (default `{d['default']}`)", d["help"]))
    return "\n".join(lines)


def sync_fault_block(text, check):
    """Returns (new_text, drift_message_or_None)."""
    try:
        b = text.index(_FAULT_BEGIN) + len(_FAULT_BEGIN)
        e = text.index(_FAULT_END)
    except ValueError:
        raise SystemExit("README fault-tolerance markers not found")
    current = text[b:e].strip("\n")
    want = render_fault_block()
    if current == want:
        print("README fault-tolerance block in sync")
        return text, None
    if check:
        return text, ("README fault-tolerance block DRIFTS from "
                      "resilience/injector.py + flags — rerun "
                      "tools/sync_readme.py")
    print("README fault-tolerance block regenerated")
    return text[:b] + "\n" + want + "\n" + text[e:], None


_SERVING_BEGIN = "<!-- BEGIN GENERATED: serving -->"
_SERVING_END = "<!-- END GENERATED: serving -->"


def render_serving_block():
    """Serving-engine config + fault surface, from the live registries
    (paddle_tpu/flags.py `serving_*` + resilience/injector.py serving
    sites) — the deployment-config doc can't drift from the code."""
    import textwrap
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu import flags
    from paddle_tpu.resilience import FAULT_SITE_DOCS

    def bullet(head, body):
        return "\n".join(textwrap.wrap(
            f"- {head} — {body}", width=76, subsequent_indent="  "))

    lines = [
        "`paddle_tpu.serving.ServingEngine` batches requests at",
        "iteration granularity: each step admits queued prompts into",
        "free KV-cache slots (prefill padded to a length bucket, one",
        "compile per bucket — and all same-bucket admissions in a step",
        "share ONE dispatch of that compile) and runs one batched",
        "decode over every occupied slot (one compile, total). KV",
        "memory is block-paged by default (`FLAGS_serving_paged`): a",
        "fixed pool of `[num_blocks, heads, block_size, head_dim]` KV",
        "blocks per layer, host-side per-request block tables fed to",
        "the jitted steps as plain inputs (block remapping never",
        "retraces), a ref-counted allocator, and a rolling-hash prefix",
        "cache — a shared system prompt prefills once and later",
        "requests reference its full blocks (copy-on-write at the",
        "boundary block), prefilling only their unshared suffix.",
        "Physical block 0 is a permanently-allocated trash block that",
        "backs table padding and absorbs overflow writes. Pool",
        "exhaustion holds the head-of-line request (FIFO order is part",
        "of the equivalence oracle) until retirements free blocks;",
        "`paged=False` falls back to the dense per-slot rows. With",
        "`FLAGS_serving_spec_tokens` = K > 0 the decode becomes",
        "draft–verify speculative decoding: an n-gram self-drafter",
        "proposes K tokens per slot from the request's own generated",
        "suffix (no second model), one fixed-shape verify forward",
        "scores all K+1 positions, the accepted prefix commits to the",
        "slot's KV cache and the rejected tail's write offset rolls",
        "back — greedy output stays token-identical to K=0. `submit()`",
        "returns a request handle; `results()` collects them;",
        "`serving.ServingHTTPServer` is the JSON front end",
        "(`POST /v1/generate` — with an optional integer `priority`",
        "field — `GET /v1/stats`, `GET /health`; 429 on admission",
        "backpressure carries a `Retry-After` header sized by the",
        "engine's predicted-TTFT model and a `reason` in the body).",
        "Per-phase latency lands in `monitor.stats()` as",
        "`STAT_serving_prefill_ms` / `STAT_serving_decode_ms` /",
        "`STAT_serving_verify_ms`; acceptance as",
        "`STAT_serving_spec_proposed` / `STAT_serving_spec_accepted`;",
        "`engine.stats()` (merged into `GET /v1/stats`) adds",
        "time-to-first-token and time-per-output-token percentiles",
        "(`ttft_p50_ms` / `ttft_p99_ms` / `tpot_p50_ms` /",
        "`tpot_p99_ms`), the speculative `spec_acceptance_rate`, and —",
        "paged — the block-pool accounting (`kv_blocks_used` /",
        "`kv_blocks_free`, also exported as gauges on `GET /metrics`)",
        "plus token-granular `prefix_hit_rate` from",
        "`STAT_serving_prefix_hits` / `_misses`.",
        "",
        "The paged decode/verify hot path has two lowerings, selected",
        "by `FLAGS_serving_attn_impl`: `xla` composes gather ->",
        "masked-softmax attention from the block pool, while `pallas`",
        "runs the fused `ops.pallas.paged_attention` kernel — the block",
        "table is scalar-prefetched and each grid step streams ONE",
        "physical KV block from the pool into VMEM through the table",
        "lookup (flash-style online softmax; the `[b, h, capacity, d]`",
        "gathered view is never materialized). Both lowerings are",
        "token-identical by construction and CI oracle. Independently,",
        "`FLAGS_serving_kv_dtype=int8` quantizes the KV pool to int8",
        "codes with per-block-per-head absmax scales (~4x more KV",
        "positions in the same pool bytes): writes go through a",
        "quantizing scatter whose scales only grow — committed codes",
        "never drift when quieter rows land later — and both lowerings",
        "apply the identical `codes * scale / 127` dequantization.",
        "The engine reports the high-water dequantization error as",
        "`kv_quant_max_abs_err` in `stats()` and as the",
        "`serving_kv_dequant_max_abs_err` gauge on `GET /metrics`.",
        "`BENCH_MODEL=serving` measures pallas-vs-xla tokens/s and the",
        "int8-vs-f32 max-concurrency gain at equal pool bytes.",
        "",
        "Scaling is two orthogonal axes. `FLAGS_serving_mesh=DxM` (or",
        "`ServingEngine(mesh=...)`) runs ONE engine tensor-parallel on a",
        "`(\"data\", \"model\")` device mesh: params and the paged KV",
        "pool are placed with `NamedSharding` under the `serving_tp`",
        "rule table (attention heads / MLP hidden split on `model`;",
        "the pool's heads axis likewise), and every compiled step runs",
        "under pjit with explicit in/out shardings while the host-side",
        "block tables stay replicated plain inputs — block remapping",
        "still never retraces. Tokens are bit-identical to the",
        "single-device engine (the 1x1 mesh is a CI oracle; a real",
        "head-split is exercised on the virtual-device mesh).",
        "`FLAGS_serving_replicas=N` (or `serving.ReplicaRouter`) is the",
        "data-parallel axis: N engine replicas behind one `submit()`,",
        "routed least-loaded by queue depth with free KV blocks as the",
        "tiebreak; full replicas shed through the same `QueueFullError`",
        "429 path, and `drain()` stops admissions and runs every",
        "replica to idle for rolling deploys. Replicas share the model",
        "and therefore the per-model unified step-compile cache — N",
        "replicas compile each step once, total, and a mesh engine pays",
        "exactly one extra compile per step kind (its entries are keyed",
        "on the mesh), an invariant `analysis.recompile` predicts and",
        "`tools/obs_smoke.py` asserts against observed counts.",
        "`engine.stats()` reports `mesh_shape`; `router.stats()` adds",
        "per-replica queue depths and free blocks; `GET /metrics` grows",
        "`serving_mesh_devices`, `serving_replicas` and per-replica",
        "`serving_queue_depth` gauges, and the run log records",
        "`serving_route` / `serving_drain` events.",
        "",
        "Admission is SLO-aware. With `FLAGS_serving_slo_ttft_ms` > 0",
        "(or `ServingEngine(slo_ttft_ms=...)`) every `submit()` first",
        "predicts the request's time-to-first-token from live state —",
        "queue depth in prefill waves, the per-bucket prefill cost, and",
        "a decode time-per-output-token EWMA (pin both via",
        "`slo_prefill_ms` / `slo_tpot_ms` for deterministic tests) —",
        "and rejects requests that cannot meet the deadline instead of",
        "queueing doomed work; the 429 carries a `Retry-After` sized by",
        "that same prediction. Requests carry an integer priority class",
        "(lower = more urgent, default 1, FIFO within a class); when",
        "the queue is full, an urgent arrival preemptively sheds the",
        "newest strictly-lower-priority queued request",
        "(`FLAGS_serving_priority_preempt`), and queued requests whose",
        "deadline has already expired are shed before ever reaching",
        "prefill. Every loss is accounted: `engine.stats()` reports",
        "per-reason shed counts (`queue_full | slo | deadline |",
        "preempted | fault | drain`) plus `slo_attainment` — the",
        "fraction of completed requests whose first token met the",
        "deadline, i.e. the goodput numerator — exported as the",
        "`serving_shed_total{reason=,priority=}` counter and",
        "`serving_slo_attainment` gauge on `GET /metrics`. All of this",
        "is host-side queue surgery: zero new XLA compiles, an",
        "invariant `analysis.recompile.predict_serving_compiles`",
        "encodes and CI asserts. On the router, `FLAGS_serving_autoscale",
        "=MIN:MAX` (or an `AutoscalePolicy`) grows/shrinks the replica",
        "set from mean queue depth with hysteresis + cooldown —",
        "retiring replicas drain in the background, admissions route",
        "around them — and `drain()` returns the count of requests shed",
        "while giving up. `tools/loadgen.py` closes the loop: an",
        "open-loop (arrivals don't wait on completions) load generator",
        "with Poisson / bursty (Markov-modulated) / diurnal arrival",
        "processes, mixed prompt/output-length and priority",
        "distributions, and fully replayable seeds — same seed, byte-",
        "identical arrival trace and identical admit/shed decisions. It",
        "drives an engine or router directly (no HTTP in the loop) and",
        "reports goodput (SLO-met completions/s), attainment, per-",
        "reason sheds, TTFT/TPOT percentiles, and leaked KV blocks",
        "(must be zero). CI runs a seeded clean + chaos-crossover gate;",
        "`BENCH_MODEL=loadgen` measures SLO-aware vs depth-only goodput",
        "at equal offered load and the graceful-degradation contract",
        "under injected faults.",
        "",
        "Prefill and decode can also split into dedicated roles.",
        "`FLAGS_serving_disagg=PxD` (or `serving.DisaggRouter`) runs a",
        "disaggregated fleet: P prefill workers admit and prefill,",
        "then hand each request off through a bounded queue",
        "(`FLAGS_serving_handoff_queue`; a full queue backpressures",
        "admission instead of buffering unboundedly) to D decode",
        "workers as an ownership-transfer record — the request, its",
        "first token, and its physical KV blocks. Co-located roles",
        "share one block pool, so adoption is a zero-copy ref-count",
        "splice of the exported block table; cross-pool adoption is an",
        "all-or-nothing block copy that releases the source blocks",
        "only once every destination block is committed. Routing is",
        "prefix-affine (`FLAGS_serving_prefix_affinity`): a fleet-wide",
        "rolling-hash index over published prefix chains steers each",
        "prompt to the prefill worker already holding its longest",
        "cached prefix (verified against the worker's live pool before",
        "use, so stale entries can't misroute), falling back to least-",
        "loaded. The split adds ZERO compiles — both roles reuse the",
        "per-model step cache, which keys on geometry, never role —",
        "an invariant `predict_serving_compiles(disagg=...)` encodes",
        "and CI asserts, alongside the token-identity oracle against",
        "the symmetric `ReplicaRouter` (prefix affinity on and off,",
        "speculative K>0, int8 KV). `router.stats()` reports handoff",
        "and affinity counters plus the fleet prefix hit rate;",
        "`GET /metrics` grows `serving_disagg_workers`,",
        "`serving_handoff_queue_depth` and",
        "`serving_prefix_affinity_hits`; the run log records",
        "`serving_handoff` events, and `serving_request` arrival",
        "events feed `tools/trace_convert.py`, which turns any run log",
        "into a replayable trace for `tools/loadgen.py --replay` —",
        "re-run production arrivals against a different topology,",
        "byte-identical. Chaos is first-class: the `serving.handoff`",
        "fault site sheds or retries cleanly, and",
        "`kill_prefill_worker()` re-homes queued work, purges the dead",
        "worker's affinity entries and sheds in-flight handoffs with",
        "zero leaked blocks. `BENCH_MODEL=loadgen` compares the fleet",
        "against a symmetric router at equal worker count (TTFT p95 +",
        "goodput; the win is gated on real TPU hardware).",
        "",
        "Decoding is per-request *data* on the same compiled engine.",
        "Every `submit()` (and `POST /v1/generate`) accepts",
        "`temperature` / `top_k` / `top_p` / `stop` / `seed` /",
        "`json_mode` — a `serving.DecodeParams` per request — and the",
        "engine batches them into fixed-shape per-slot tensors fed to",
        "the jitted steps as plain inputs, so greedy, sampled and",
        "constrained rows mix freely in one batch of one executable:",
        "zero new compiles, an invariant",
        "`predict_serving_compiles(sampling=...)` encodes and CI",
        "asserts. Per-request `jax.random` keys derive from the seed",
        "alone and advance functionally inside the step (fixed fan-out",
        "per row per step), so sampled output is a pure function of",
        "the request — engine restarts, replica routing and the",
        "disaggregated fleet replay the same bytes, and `temperature",
        "0` rows stay bit-identical to the pre-sampling engine.",
        "Speculative decoding verifies sampled rows by rejection",
        "sampling: the committed-token law matches non-speculative",
        "sampling exactly (greedy rows keep the prefix-match rule,",
        "token-identical). `json_mode` is constrained decoding:",
        "construct the engine with a `serving.JsonGrammar` (a",
        "char-level pushdown over an explicit id -> string token",
        "table; `json_token_strings(vocab)` is the canonical one) and",
        "masked rows emit syntactically valid JSON by construction —",
        "the budget-aware mask only opens transitions completable",
        "within the request's remaining tokens. Multi-tenant LoRA",
        "applies the block-table trick to weights:",
        "`FLAGS_serving_lora_rank` > 0 builds a paged",
        "`serving.LoRAPool` of per-tenant low-rank adapter factors",
        "(page 0 = base, all-zero), requests name a `tenant`, and the",
        "per-slot page ids plus the pool arrays ride the compiled",
        "steps as two more plain inputs — per-row adapter deltas are",
        "gathered inside the step, so tenants share one engine, one",
        "KV pool and one executable. `load_adapter()` /",
        "`evict_adapter()` are functional pool writes at runtime",
        "(eviction refuses while a tenant has in-flight requests;",
        "`leaked()` must be zero after drain, chaos included);",
        "routers auto-create one shared pool across replicas and",
        "roles, resolving tenants by name so page ids never travel.",
        "`engine.stats()` reports per-tenant goodput under `tenants`",
        "and the adapter roster under `lora`; `GET /metrics` grows",
        "the `serving_lora_adapters_loaded` gauge; the run log",
        "records `serving_lora_load` events; and",
        "`tools/loadgen.py --tenant-mix base:0.5,acme:0.3,zeta:0.2",
        "--sample-frac 0.5 --lora-rank 2` drives the mixed-tenant",
        "sampled workload with per-tenant goodput in the report and a",
        "`--expect-zero-new-compiles` gate.",
        "",
        "The request lifecycle is robust end to end. `cancel(rid)` (or",
        "`DELETE /v1/requests/<id>`; a broken client pipe cancels too)",
        "terminates a request at whatever stage it has reached —",
        "queued, mid-prefill, awaiting handoff, or mid-decode —",
        "releasing every KV block and LoRA pin, purging affinity",
        "entries and deduping re-homed copies; it is idempotent and",
        "pure host-side queue/slot surgery (zero new compiles,",
        "`predict_serving_compiles(cancel=N)` is a validated no-op),",
        "and the accounting identity extends to `completed + rehomed +",
        "shed + canceled == offered`. `submit(deadline_ms=...)` is a",
        "hard end-to-end deadline carried through handoffs and",
        "re-homes: every stage boundary and every between-steps reap",
        "sweep enforces it, so an expired request is canceled — not",
        "completed — within one step and its slot admits waiting work",
        "in that same step. Tail latency is hedged",
        "(`FLAGS_serving_hedge_ms`; negative = auto from the live TTFT",
        "p95): when the router predicts a slow first token it arms a",
        "hedge, fires a clone to the second-best replica after the",
        "delay, takes whichever first token lands first and cancels",
        "the loser leak-free (`canceled{reason=hedge_lose}`), with",
        "fired volume bounded by a `FLAGS_serving_hedge_budget` token",
        "bucket (`fired <= 1 + budget * offered`). Retries on the",
        "serving hot paths (`serving.route | serving.handoff |",
        "serving.replica`) share one fleet-wide `RetryBudget`",
        "(`FLAGS_retry_budget_*`): successes fund retries, correlated",
        "failure drains the bucket and sheds would-be storms as",
        "backpressure, and a per-replica circuit breaker stops routing",
        "to repeat offenders. Observability rides along:",
        "`serving_canceled_total{reason=}`,",
        "`serving_hedges_total{outcome=}` and",
        "`serving_retry_budget_remaining` on `GET /metrics`,",
        "`serving_cancel` / `serving_hedge` run-log events, and",
        "cancel / hedge / hedge_win / hedge_lose trace marks.",
        "`tools/loadgen.py --closed-loop N --abandon-frac F` makes a",
        "seeded subset of clients hang up mid-decode (abandonment",
        "rides the trace, so replays reproduce the cancels byte-",
        "identically), `--straggler I:MS --hedge-ms D` races hedges",
        "against a deterministic slow replica, and CI gates the lot:",
        "hedged goodput must beat unhedged under a straggler + chaos",
        "kill + 10% abandonment at zero leaks and zero new compiles,",
        "and the soak re-asserts the extended identity and the hedge",
        "budget envelope.",
        "",
        "Session capacity scales past HBM through the host-RAM KV",
        "tier (`FLAGS_serving_host_tier`, serving/kv_tier.py): a",
        "fleet-shared `serving.HostBlockStore` parks cold prefix",
        "chains in pinned host memory, int8-at-rest on the same",
        "absmax grid the device pool quantizes with, behind a",
        "refcounted allocator whose `leaked()` must read zero after",
        "drain just like the device pool's. A `serving.TierManager`",
        "demotes idle chains between steps (LRU, leaf-first,",
        "double-buffered staging copies off the step path; cadence",
        "via `FLAGS_serving_demote_idle_ms`), promotes them back",
        "all-or-nothing on demand at admission, and dedups fleet-wide",
        "— two workers demoting the same system prompt store it once.",
        "`submit(session=...)` turns that into resumable",
        "conversations: the engine stores each finished turn's",
        "context in a `serving.SessionStore`, prepends it to the next",
        "turn, and re-prefills only the unshared suffix, so a",
        "demoted conversation resumes *token-identically* (spec K>0,",
        "int8 device KV and LoRA tenant pins included) and concurrent",
        "sessions are bounded by host blocks, not device blocks.",
        "Routers build ONE tier across replicas and roles, the fleet",
        "prefix index keeps a killed worker's entries alive as",
        "host-tier markers whenever the chain is still promotable,",
        "and migration faults (`serving.migrate`) retry per",
        "`RetryPolicy` without leaking either tier. Every migration",
        "is host-side numpy/block surgery —",
        "`predict_serving_compiles(host_tier=True, sessions=N)` is a",
        "validated no-op. `GET /metrics` grows",
        "`serving_kv_migrations{dir=}`, tier-labelled block gauges",
        "and `serving_sessions_{resident,host,resumed}`; the run log",
        "records `serving_kv_demote` / `serving_kv_promote` /",
        "`serving_session_resume`; and `tools/loadgen.py",
        "--returning-frac F --turns-per-session A:B --host-blocks N`",
        "drives seeded multi-turn sessions with idle gaps (session",
        "rows ride the trace for byte-identical replay) and gates",
        "resumed sessions, zero leaks on both tiers, zero new",
        "compiles after warmup, and peak concurrent sessions above",
        "the device pool's block count.",
        "",
        "Flags:",
        "",
    ]
    defs = flags.list_flags()
    for name in sorted(defs):
        if name.startswith("serving_"):
            d = defs[name]
            lines.append(bullet(
                f"`FLAGS_{name}` (default `{d['default']}`)", d["help"]))
    lines += [
        "",
        "Tuning `FLAGS_serving_spec_tokens`: each verify step scores",
        "K+1 positions whether or not the drafts are accepted, so the",
        "win is `(1 + K * acceptance_rate)` tokens per step against a",
        "step that costs slightly more than plain decode. Watch",
        "`spec_acceptance_rate` in `GET /v1/stats`: repetitive or",
        "templated traffic (code, markup, retrieval-augmented answers)",
        "sustains 0.5+ and profits from K of 4-8; low-entropy-free chat",
        "traffic near 0.2 wants K of 2-3 or 0. Each request reserves K",
        "rows of slot headroom, so `prompt + max_new_tokens + K` must",
        "fit in `FLAGS_serving_max_len`. `BENCH_MODEL=serving` reports",
        "spec vs non-spec tokens/s and the measured acceptance rate on",
        "a repetitive-suffix workload.",
        "",
        "Fault sites (see Fault tolerance for the spec grammar):",
        "",
    ]
    lines += [bullet(f"`{site}`", doc)
              for site, doc in FAULT_SITE_DOCS.items()
              if site.startswith("serving.")]
    return "\n".join(lines)


def sync_serving_block(text, check):
    """Returns (new_text, drift_message_or_None)."""
    try:
        b = text.index(_SERVING_BEGIN) + len(_SERVING_BEGIN)
        e = text.index(_SERVING_END)
    except ValueError:
        raise SystemExit("README serving markers not found")
    current = text[b:e].strip("\n")
    want = render_serving_block()
    if current == want:
        print("README serving block in sync")
        return text, None
    if check:
        return text, ("README serving block DRIFTS from the serving "
                      "flag/site registries — rerun tools/sync_readme.py")
    print("README serving block regenerated")
    return text[:b] + "\n" + want + "\n" + text[e:], None


_TRAINSERVE_BEGIN = "<!-- BEGIN GENERATED: train-serve -->"
_TRAINSERVE_END = "<!-- END GENERATED: train-serve -->"
_TRAINSERVE_FLAGS = ("zero_stage",)


def render_trainserve_block():
    """ZeRO optimizer plane + live weight hot-swap, with the
    `zero_*` flag rows pulled from the live flag registry."""
    import textwrap
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu import flags

    def bullet(head, body):
        return "\n".join(textwrap.wrap(
            f"- {head} — {body}", width=76, subsequent_indent="  "))

    lines = [
        "Training and serving close into one loop: train with the",
        "optimizer state ZeRO-sharded across the data axis, publish the",
        "weights through a checkpoint, and hot-swap them into a",
        "*running* `ServingEngine` without draining requests or paying",
        "a single new XLA compile.",
        "",
        "`paddle_tpu.distributed.zero.zero_train_step(fn, layers=...,",
        "optimizers=..., mesh=..., stage=...)` is a drop-in for",
        "`jit.to_static` that implements ZeRO-1/2 purely with",
        "pjit/`NamedSharding` — no `shard_map`, no hand-written",
        "collectives. `sharding.opt_state_shardings(...)` assigns each",
        "Adam moment a `PartitionSpec` with the data axis added to its",
        "first divisible free dimension (`zero_partition_spec`), so",
        "GSPMD materializes each device's 1/dp optimizer shard and",
        "inserts the gather; stage 2 additionally annotates gradients",
        "with the same specs, turning the grad all-reduce into a",
        "reduce-scatter. Undivisible tensors fall back to their base",
        "spec (replicated moments), scalars (`_lr`, Adam step counts)",
        "stay replicated, and tensor-parallel param rules compose:",
        "moments shard on BOTH the TP axis and the data axis. The",
        "wrapper publishes live per-device byte accounting",
        "(`zero_opt_bytes` / `zero_opt_bytes_per_device` gauges,",
        "measured from `addressable_shards`, plus",
        "`zero.byte_report(...)`), and",
        "`tools/lint_sharding.py --zero-stage N` folds the same",
        "estimate into the lint report before any training run.",
        "",
        "The serve half: `zero.save_train_state(saver, layers,",
        "optimizers, step)` gathers the sharded optimizer state and",
        "writes one `CheckpointSaver` checkpoint (params under",
        "`param/<name>`, moments under `opt<i>/<key>`, the ZeRO stage",
        "in metadata); `zero.weights_from_checkpoint(state)` strips it",
        "back to a `{name: array}` mapping; and",
        "`ServingEngine.swap_weights(weights, reset_costs=True)`",
        "installs the new weights between engine steps under the step",
        "lock — names/shapes validated, arrays re-placed onto the",
        "engine's mesh per the `serving_tp` rules, the admission",
        "controller's learned cost model optionally reset. Because",
        "every compiled prefill/decode/verify step takes the params as",
        "a donated *input* (not a closure constant), the unified step",
        "cache is untouched: a swap costs ZERO new compiles —",
        "`analysis.predict_serving_compiles(..., weight_swaps=N)` is a",
        "validated no-op — and the next step serves the new weights.",
        "`ReplicaRouter.swap_weights(...)` rolls the swap across",
        "replicas one engine at a time (drain-free; stragglers keep",
        "serving the old version until their turn). Each swap bumps the",
        "`serving_weight_version` gauge and logs a",
        "`serving_weight_swap` run-log event.",
        "",
        "`tools/zero_smoke.py` (CI gate) trains 2 ZeRO steps at dp=2,",
        "asserts per-device optimizer bytes ~1/2 of total with",
        "loss-for-loss parity against the unsharded baseline, then",
        "publishes and hot-swaps into a live engine asserting",
        "token-correct output and 0 compiles. `BENCH_MODEL=zero`",
        "benchmarks the per-device byte ratio and step time against",
        "replicated Adam.",
        "",
        "Flags:",
        "",
    ]
    defs = flags.list_flags()
    for name in _TRAINSERVE_FLAGS:
        d = defs[name]
        lines.append(bullet(
            f"`FLAGS_{name}` (default `{d['default']}`)", d["help"]))
    return "\n".join(lines)


def sync_trainserve_block(text, check):
    """Returns (new_text, drift_message_or_None)."""
    try:
        b = text.index(_TRAINSERVE_BEGIN) + len(_TRAINSERVE_BEGIN)
        e = text.index(_TRAINSERVE_END)
    except ValueError:
        raise SystemExit("README train-serve markers not found")
    current = text[b:e].strip("\n")
    want = render_trainserve_block()
    if current == want:
        print("README train-serve block in sync")
        return text, None
    if check:
        return text, ("README train-serve block DRIFTS from the "
                      "zero/flag registries — rerun "
                      "tools/sync_readme.py")
    print("README train-serve block regenerated")
    return text[:b] + "\n" + want + "\n" + text[e:], None


_OBS_BEGIN = "<!-- BEGIN GENERATED: observability -->"
_OBS_END = "<!-- END GENERATED: observability -->"
_OBS_FLAGS = ("warn_recompiles", "runlog_dir", "runlog_max_mb",
              "serving_trace", "serving_trace_keep",
              "serving_devprof", "serving_devprof_sample",
              "devprof_peak_flops", "devprof_peak_hbm_gbps")


def render_observability_block():
    """Instrument inventory + run-log event kinds + flags, from the
    live registries (observability.INSTRUMENT_DOCS / EVENT_DOCS and
    paddle_tpu/flags.py)."""
    import textwrap
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu import flags, observability

    def bullet(head, body):
        return "\n".join(textwrap.wrap(
            f"- {head} — {body}", width=76, subsequent_indent="  "))

    lines = [
        "`paddle_tpu.observability` is the one metrics plane the whole",
        "framework reports into: a thread-safe registry of typed",
        "Counter / Gauge / Histogram instruments (fixed log-scale",
        "buckets, so p50/p95/p99 are derivable without storing",
        "samples), an XLA compile tracker wrapping every `jax.jit`",
        "entry point (`observability.compiles()` gives per-site compile",
        "counts, wall time, and the abstract shape/dtype signature that",
        "triggered each compile), a structured JSONL run log",
        "(`observability.log_event(kind, **fields)`), and exporters:",
        "`observability.prometheus_text()` served at `GET /metrics` on",
        "`ServingHTTPServer`, `observability.snapshot()` embedded in",
        "`BENCH_*.json`, and counter/histogram summaries appended to",
        "`profiler.stop_profiler()`'s table. The `monitor.stat_*` API",
        "is a shim over the same registry.",
        "",
        "Per-request tracing rides on top",
        "(`paddle_tpu.observability.tracing`): every sampled request",
        "(`FLAGS_serving_trace`, default everything) carries its id",
        "from `submit()` through admit / prefill / handoff / decode /",
        "re-home / finish-or-shed as host-side `(kind, t, track)` marks",
        "on the engine's own clock (wall or the soak harness's virtual",
        "clock — never a jit input, so tracing is a validated",
        "zero-compile no-op: `predict_serving_compiles(...,",
        "tracing=True)`). A kill stitches the survivor's spans onto the",
        "original trace, so a re-homed request is ONE timeline whose",
        "re-home penalty is its own blame component. `tracing.blame()`",
        "decomposes each finished request's E2E into queue | prefill |",
        "decode | handoff | rehome components that sum *exactly* to the",
        "measured E2E (and the prefix up to the first token exactly to",
        "TTFT) — an accounting identity, not an approximation;",
        "`blame_summary()` aggregates fleet-wide shares, p95s and the",
        "component that dominates the E2E-p95 tail.",
        "`export_chrome_trace()` writes a Perfetto-loadable chrome",
        "trace — one named track per engine/replica/role, one flow per",
        "request stitching its spans across tracks — and",
        "`export_spans_jsonl()` the same spans as JSONL; both",
        "canonicalize ids and track names so two same-seed virtual-",
        "clock runs export byte-identical files (a CI flake guard).",
        "`python tools/trace_summary.py TRACE --blame` prints the",
        "component blame table from either export;",
        "`GET /v1/requests/<id>` on `ServingHTTPServer` serves one",
        "request's live timeline + blame (404 once evicted from the",
        "`FLAGS_serving_trace_keep` ring); and",
        "`tracing.window_snapshots(...)` folds finished traces into",
        "per-window TTFT histograms, SLO attainment and burn rate",
        "(`(1 - attainment) / (1 - target)`) — the",
        "`serving_slo_burn_rate` gauge and the per-window report of",
        "`tools/soak.py --trace-out`.",
        "",
        "The device-cost observatory",
        "(`paddle_tpu.observability.devprof`, off by default behind",
        "`FLAGS_serving_devprof`) adds the device half: every compile",
        "of a tracked serving entry records the lowered computation's",
        "XLA `cost_analysis()` (flops / HBM bytes / output bytes) into",
        "`devprof.cost_table()` and the `xla_cost{fn,metric}` gauges",
        "(a re-lowering of the raw function, so the compile counters",
        "never move — `predict_serving_compiles(..., devprof=True)` is",
        "a validated no-op), and a",
        "`FLAGS_serving_devprof_sample`-rate `block_until_ready` timer",
        "around step dispatch (deterministic Knuth hash of the",
        "dispatch counter; skipped dispatches keep the async and",
        "dispatch-ahead paths untouched) feeds the per-entry",
        "`serving_device_step_ms` histogram, per-step roofline",
        "verdicts (compute-bound / hbm-bound / host-bound, against",
        "`FLAGS_devprof_peak_flops` / `FLAGS_devprof_peak_hbm_gbps` or",
        "per-platform nominals) and the live `serving_mfu`,",
        "`serving_hbm_util` and `serving_host_overhead_share` gauges.",
        "The sampled device fraction splits `tracing.blame()`'s",
        "`decode` component into `decode_device` + `decode_host` with",
        "the exact-reconciliation identity preserved",
        "(`tools/trace_summary.py --blame` renders the split and the",
        "roofline table). `tools/perf_ledger.py` appends every",
        "`bench.py` / `tools/loadgen.py --ledger` /",
        "`tools/soak.py --ledger` run as one schema'd JSONL row",
        "(goodput, TTFT/TPOT p95, MFU, host-overhead share,",
        "cost-table digest, git rev) and",
        "`python tools/perf_regress.py LEDGER --baseline",
        "tools/perf_baseline.json` gates the latest row against the",
        "committed baseline with per-metric noise tolerance (exit",
        "nonzero on regression — the ci.sh perf gate; refresh the",
        "baseline with `--write-baseline`).",
        "",
        "Instruments:",
        "",
    ]
    lines += [bullet(f"`{name}`", doc)
              for name, doc in observability.INSTRUMENT_DOCS.items()]
    lines += [
        "",
        "Run-log event kinds (one JSON line each, stamped with a",
        "monotonic `seq`/`ts`/`mono`; summarize with",
        "`python tools/trace_summary.py <runlog.jsonl>`, which also",
        "reads the profiler's chrome-trace JSON):",
        "",
    ]
    lines += [bullet(f"`{kind}`", doc)
              for kind, doc in observability.EVENT_DOCS.items()]
    lines += [
        "",
        "Example scrape:",
        "",
        "```",
        "$ curl -s localhost:$PORT/metrics | grep -m4 -E 'serving|compiles'",
        "# TYPE STAT_serving_tokens counter",
        "STAT_serving_tokens 128",
        "# TYPE xla_compiles counter",
        'xla_compiles{bucket="16",fn="serving_prefill"} 1',
        "```",
        "",
        "Flags:",
        "",
    ]
    defs = flags.list_flags()
    for name in _OBS_FLAGS:
        d = defs[name]
        lines.append(bullet(
            f"`FLAGS_{name}` (default `{d['default']}`)", d["help"]))
    return "\n".join(lines)


def sync_observability_block(text, check):
    """Returns (new_text, drift_message_or_None)."""
    try:
        b = text.index(_OBS_BEGIN) + len(_OBS_BEGIN)
        e = text.index(_OBS_END)
    except ValueError:
        raise SystemExit("README observability markers not found")
    current = text[b:e].strip("\n")
    want = render_observability_block()
    if current == want:
        print("README observability block in sync")
        return text, None
    if check:
        return text, ("README observability block DRIFTS from the "
                      "observability/flag registries — rerun "
                      "tools/sync_readme.py")
    print("README observability block regenerated")
    return text[:b] + "\n" + want + "\n" + text[e:], None


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--check", action="store_true",
                   help="fail on drift instead of rewriting")
    args = p.parse_args()

    readme = os.path.join(REPO, "README.md")
    with open(readme) as f:
        text = f.read()
    orig = text
    drifts = []
    for sync in (sync_headline, sync_checks_block, sync_fault_block,
                 sync_serving_block, sync_trainserve_block,
                 sync_observability_block):
        text, drift = sync(text, args.check)
        if drift:
            drifts.append(drift)
    if drifts:
        print("\n".join(drifts))
        return 1
    if text != orig:
        with open(readme, "w") as f:
            f.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())

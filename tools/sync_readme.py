#!/usr/bin/env python
"""Regenerate README headline numbers from the latest BENCH_r*.json.

Three rounds in a row the hand-written README headline drifted from the
measured artifact; this makes the artifact the single source of truth:

    python tools/sync_readme.py          # rewrite the GPT headline line
    python tools/sync_readme.py --check  # exit 1 on drift (CI gate)

The GPT flagship bullet between the BEGIN/END markers is generated;
everything else in README.md stays hand-written.
"""

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def latest_bench():
    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if not paths:
        raise SystemExit("no BENCH_r*.json artifact found")
    with open(paths[-1]) as f:
        data = json.load(f)
    return paths[-1], data.get("parsed") or json.loads(
        data["tail"].strip().splitlines()[-1])


_FLAGSHIP_NAMES = {
    "gpt2_345m_mfu": "GPT-2 345M",
    "gpt2-medium_mfu": "GPT-2 345M",
    "gpt2-1p1b_mfu": "GPT-2-class 1.1B (d=128)",
    "gpt2-1p3b_mfu": "GPT-2-class 1.3B (d=128)",
}


def headline(parsed, src):
    toks = parsed.get("tokens_per_sec_per_chip")
    metric = parsed.get("metric")
    name = _FLAGSHIP_NAMES.get(metric, metric or "flagship")
    via = ("the Pallas flash-attention kernels + per-block recompute + "
           "grads-internal trace-once compiled train step"
           if "1p" in (metric or "") else
           "the Pallas flash-attention kernels + trace-once compiled "
           "train step")
    return (
        f"- {name} training at **{parsed['value']:.2f}% MFU** "
        f"(batch {parsed['batch']}, seq {parsed['seq']}, bf16, bf16 AdamW "
        f"moments; {toks / 1000:.1f}k tokens/s/chip) — "
        f"{parsed['vs_baseline']:.2f}x the 40% north-star target — via "
        f"{via}. "
        f"[generated from {os.path.basename(src)}]"
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--check", action="store_true",
                   help="fail on drift instead of rewriting")
    args = p.parse_args()

    src, parsed = latest_bench()
    if parsed.get("metric") not in _FLAGSHIP_NAMES:
        print(f"latest artifact is {parsed.get('metric')}, not a GPT "
              "flagship; nothing to sync")
        return 0
    want = headline(parsed, src)

    readme = os.path.join(REPO, "README.md")
    with open(readme) as f:
        text = f.read()
    # the generated bullet: starts "- GPT-2 345M training" and ends with
    # the "[generated from ...]" stamp (possibly wrapped over lines)
    pat = re.compile(
        r"- GPT[^\n]*training at[^\n]*(?:\n(?!-)[^\n]*)*")
    m = pat.search(text)
    if not m:
        raise SystemExit("README GPT headline bullet not found")
    current = m.group(0)
    # wrap the generated line to the README's 78-col style
    import textwrap
    wrapped = "\n".join(textwrap.wrap(
        want, width=76, initial_indent="", subsequent_indent="  "))
    if current.strip() == wrapped.strip():
        print("README headline in sync")
        return 0
    if args.check:
        print("README headline DRIFTS from the bench artifact:\n"
              f"  readme: {' '.join(current.split())[:100]}...\n"
              f"  artifact: {' '.join(wrapped.split())[:100]}...")
        return 1
    text = text[:m.start()] + wrapped + text[m.end():]
    with open(readme, "w") as f:
        f.write(text)
    print(f"README headline updated from {os.path.basename(src)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

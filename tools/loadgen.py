#!/usr/bin/env python
"""Open-loop load generator for the serving plane.

Drives a :class:`ServingEngine` or :class:`ReplicaRouter` directly —
no HTTP hop — with a replayable synthetic arrival process, and reports
goodput under the TTFT SLO: the regression-locked "real traffic"
scenario (ROADMAP item 2, ``BENCH_MODEL=loadgen``).

Arrival processes (all derived from one ``np.random.RandomState(seed)``
by thinning against the peak rate, so the same seed reproduces the
same trace byte for byte):

- ``poisson``: constant-rate open-loop arrivals (exponential
  inter-arrival gaps) — the classic steady-state model;
- ``bursty``: a two-state Markov-modulated Poisson process — calm
  periods at ``rate`` alternating with bursts at ``rate *
  burst_factor``, sojourn times exponential around ``switch_every``
  (calm) and ``switch_every * burst_fraction`` (burst). This is the
  overload-robustness workload: mean load may be serveable while
  bursts are not;
- ``diurnal``: sinusoidal rate ``rate * (1 + amplitude *
  sin(2*pi*t/period))`` — a whole "day" of traffic compressed into
  ``duration`` seconds.

Each arrival carries a prompt sampled from a mixed length distribution
(70% "chat-short" uniform on the lower half of ``prompt_tokens``, 30%
"doc-long" uniform on the upper half), a new-token budget sampled the
same way from ``new_tokens``, and a priority class drawn from
``priority_mix`` (lower = more urgent). With ``sample_frac`` /
``tenant_mix`` (CLI: ``--sample-frac``, ``--tenant-mix
base:0.5,acme:0.3,zeta:0.2``, ``--lora-rank``) arrivals additionally
carry seeded per-request decode params (temperature / top-k / top-p /
seed) and a LoRA tenant name — the mixed-traffic workload behind the
per-tenant goodput report and the zero-new-compiles gate
(``--expect-zero-new-compiles``: sampling is data and adapter pages
are data, so post-warmup traffic must never retrace). Greedy
generators consume the RNG exactly as before, so old seeds keep old
traces. ``trace_bytes()`` serializes
the schedule canonically — the determinism tests assert two same-seed
generators produce identical bytes AND identical admit/shed decisions.

Two execution modes:

- **wall clock** (default): arrivals are released on the real clock
  and the target is stepped between releases — the bench/CI path;
- **virtual clock** (``clock=VirtualClock()``, engines constructed
  with ``clock=vc.now`` and *pinned* predictor costs): the loop
  advances time by ``step_cost_ms`` per scheduler step and jumps
  across idle gaps. Fully deterministic — timestamps, TTFTs, admit
  and shed decisions replay exactly; used by the determinism tests
  and the obs_smoke loadgen phase (where it also proves admission
  adds zero XLA compiles).

Two release disciplines:

- **open loop** (default): arrivals are released at their scheduled
  times no matter how the target is doing — the overload-honest
  model (a slow server does not slow the offered load);
- **closed loop** (``closed_loop=N`` / ``--closed-loop N --think-time
  -ms A:B``): N clients each wait for their previous request to
  finish, think for a seeded uniform A..B ms, then release the next
  scheduled arrival's content. Think times come from a *separate*
  RandomState, so open-loop seeds keep producing byte-identical
  schedules.

Abandonment (``--abandon-frac F``, closed loop only): a seeded
fraction of clients hang up mid-decode — each fires a fleet
``cancel(reason="disconnect")`` once 25-75% of its token budget has
landed. The draws come from a dedicated RandomState (abandon-free
seeds keep their byte-identical traces) and ride the trace rows as
column 10, so an abandonment workload replays byte for byte; the
report counts ``canceled`` per reason and ``abandoned`` clients, and
``leaked_kv_blocks`` must stay 0 regardless of where the cancels
landed. With a hedging router (``--hedge-ms``, ``--hedge-budget``)
the report grows a ``hedges`` section — fired/wins/loses, hedge rate
vs offered load, win rate, and the duplicated-token cost of racing
(``--straggler I:MS`` makes replica I a deterministic straggler for
the hedge to beat).

Returning users (``--returning-frac F --turns-per-session A:B``,
needs ``--host-blocks N`` for the host KV tier): a seeded fraction of
arrivals open a multi-turn session — turn 1 is the arrival itself,
follow-up turns arrive after idle gaps (long enough for the demotion
sweep to park the context in host RAM) and submit with
``session=<id>`` so the engine prepends the stored context and
resumes token-identically off a host-promoted chain. Session draws
come from a dedicated RandomState (session-free seeds keep their
byte-identical traces) and ride the trace rows as column 11, so a
returning-users workload replays byte for byte; the report grows a
``sessions`` section — offered/turns/resumed, host-block peaks, the
zero-leak identity for the host half, and the sessions-beyond-HBM
capacity gate (``--expect-capacity-gt-device``: peak concurrent
sessions must exceed the device pool's block count).

Chaos replay: a trace may carry a ``chaos`` schedule (rows of
``[t, kind, index]``, kind in kill | restart | kill_decode —
``tools/trace_convert.py`` extracts them from a live run's
``serving_replica_kill`` / ``serving_replica_recover`` /
``serving_worker_kill`` events). ``run()`` fires each event when the
clock passes its ``t``, so a recorded kill/restart schedule replays
deterministically alongside the arrivals.

Per-request trace rows record arrival time, admit/shed decision (with
the shed reason), TTFT, TPOT and whether the deadline was met; the
report aggregates offered load, goodput (SLO-met completions/s),
throughput, attainment, per-reason sheds, latency percentiles, leaked
KV blocks (after a prefix-cache flush; the trash block is exempt) and
the count of unexpected exceptions (the graceful-degradation contract
demands 0 even under ``FLAGS_fault_spec``).

CLI (gates live in tools/ci.sh; full flag list via --help):

  JAX_PLATFORMS=cpu python tools/loadgen.py --model gpt2-tiny \
      --mode bursty --rate 20 --duration 3 --seed 0 \
      --slo-ttft-ms 2000 --json --expect-goodput-min 0.1
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from typing import List, NamedTuple, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


class Arrival(NamedTuple):
    t: float               # seconds since the run started
    prompt: Tuple[int, ...]
    max_new_tokens: int
    priority: int
    # per-request decoding fields (sampling-as-data; the defaults
    # reproduce the pre-decoding greedy trace byte for byte)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    tenant: str = ""       # "" = base weights (no LoRA adapter)
    # client patience: > 0 means the closed-loop client hangs up
    # (fleet cancel) once this fraction of the new-token budget has
    # been produced — the abandonment workload; 0 = patient client
    abandon_after: float = 0.0
    # returning-user conversation id ("" = one-shot request): turns
    # sharing a session submit with session=<id> so the host KV tier
    # resumes the stored context after an idle gap
    session: str = ""


class VirtualClock:
    """Deterministic time source for replayable runs: pass ``vc.now``
    as the engine's ``clock`` and let the loadgen loop ``advance`` it
    a fixed cost per scheduler step."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def now(self) -> float:
        return self.t

    def advance(self, dt: float):
        if dt < 0:
            raise ValueError(f"cannot rewind the clock by {dt}")
        self.t += dt


class LoadGen:
    """Replayable open-loop traffic source; see the module docstring.

    ``rate`` is the calm/mean arrival rate in requests/s (``bursty``
    exceeds it during bursts, ``diurnal`` oscillates around it);
    ``duration`` is the arrival window in seconds — the run itself
    continues until the target drains. ``prompt_tokens`` /
    ``new_tokens`` are inclusive (lo, hi) ranges; ``priority_mix``
    maps priority class -> weight (default: everything class 1).
    """

    MODES = ("poisson", "bursty", "diurnal")

    def __init__(self, mode: str = "poisson", rate: float = 8.0,
                 duration: float = 4.0, seed: int = 0,
                 vocab_size: int = 1024,
                 prompt_tokens: Tuple[int, int] = (4, 24),
                 new_tokens: Tuple[int, int] = (2, 16),
                 priority_mix: Optional[dict] = None,
                 burst_factor: float = 8.0,
                 burst_fraction: float = 0.25,
                 switch_every: float = 1.0,
                 diurnal_period: Optional[float] = None,
                 diurnal_amplitude: float = 0.8,
                 sample_frac: float = 0.0,
                 tenant_mix: Optional[dict] = None,
                 closed_loop: int = 0,
                 think_time_ms: Tuple[float, float] = (0.0, 0.0),
                 abandon_frac: float = 0.0,
                 returning_frac: float = 0.0,
                 turns_per_session: Tuple[int, int] = (2, 4)):
        if mode not in self.MODES:
            raise ValueError(f"mode must be one of {self.MODES}, "
                             f"got {mode!r}")
        if rate <= 0 or duration <= 0:
            raise ValueError("rate and duration must be > 0")
        if not (0 < diurnal_amplitude < 1) and mode == "diurnal":
            raise ValueError("diurnal_amplitude must be in (0, 1)")
        for lo, hi, name in [(prompt_tokens[0], prompt_tokens[1],
                              "prompt_tokens"),
                             (new_tokens[0], new_tokens[1],
                              "new_tokens")]:
            if lo < 1 or hi < lo:
                raise ValueError(f"{name} must satisfy 1 <= lo <= hi")
        self.mode = mode
        self.rate = float(rate)
        self.duration = float(duration)
        self.seed = int(seed)
        self.vocab_size = int(vocab_size)
        self.prompt_tokens = (int(prompt_tokens[0]),
                              int(prompt_tokens[1]))
        self.new_tokens = (int(new_tokens[0]), int(new_tokens[1]))
        mix = priority_mix if priority_mix else {1: 1.0}
        total = float(sum(mix.values()))
        if total <= 0 or any(w < 0 for w in mix.values()):
            raise ValueError("priority_mix weights must be >= 0 with a "
                             "positive sum")
        self._pri_vals = sorted(int(p) for p in mix)
        self._pri_probs = [float(mix[p]) / total for p in self._pri_vals]
        self.burst_factor = float(burst_factor)
        self.burst_fraction = float(burst_fraction)
        self.switch_every = float(switch_every)
        self.diurnal_period = float(diurnal_period if diurnal_period
                                    else duration)
        self.diurnal_amplitude = float(diurnal_amplitude)
        # Per-request decoding mix. The decode-field draws are gated on
        # the feature being on at all so a plain greedy generator
        # consumes the RNG stream exactly as before — old seeds keep
        # producing old traces byte for byte.
        if not (0.0 <= float(sample_frac) <= 1.0):
            raise ValueError("sample_frac must be in [0, 1]")
        self.sample_frac = float(sample_frac)
        tmix = dict(tenant_mix) if tenant_mix else {}
        tt = float(sum(tmix.values()))
        if tmix and (tt <= 0 or any(w < 0 for w in tmix.values())):
            raise ValueError("tenant_mix weights must be >= 0 with a "
                             "positive sum")
        # "base" / "" both mean the base weights (no adapter page)
        self._tenant_vals = sorted(
            "" if n in ("", "base") else str(n) for n in tmix)
        self._tenant_probs = [float(tmix[n]) / tt for n in sorted(
            tmix, key=lambda n: "" if n in ("", "base") else str(n))]
        self._decoded = bool(tmix) or self.sample_frac > 0
        if closed_loop < 0:
            raise ValueError("closed_loop must be >= 0 "
                             "(0 = open loop)")
        lo, hi = (float(think_time_ms[0]), float(think_time_ms[1]))
        if lo < 0 or hi < lo:
            raise ValueError("think_time_ms must satisfy 0 <= lo <= hi")
        self.closed_loop = int(closed_loop)
        self.think_time_ms = (lo, hi)
        # Abandonment draws come from their own RandomState (like the
        # think times), so abandon-free seeds keep producing their old
        # traces byte for byte.
        if not (0.0 <= float(abandon_frac) <= 1.0):
            raise ValueError("abandon_frac must be in [0, 1]")
        self.abandon_frac = float(abandon_frac)
        self._abandon = self.abandon_frac > 0
        # Returning users: a seeded fraction of arrivals open a
        # multi-turn session — follow-up turns arrive after an idle
        # gap and submit with session=<id> so the host KV tier resumes
        # the stored context. All draws come from a dedicated
        # RandomState, so session-free seeds keep their byte-identical
        # traces.
        if not (0.0 <= float(returning_frac) <= 1.0):
            raise ValueError("returning_frac must be in [0, 1]")
        ta, tb = (int(turns_per_session[0]), int(turns_per_session[1]))
        if ta < 1 or tb < ta:
            raise ValueError(
                "turns_per_session must satisfy 1 <= A <= B")
        self.returning_frac = float(returning_frac)
        self.turns_per_session = (ta, tb)
        self._returning = self.returning_frac > 0
        #: chaos schedule replayed alongside the arrivals: dicts of
        #: {"t", "kind", "index"}; populated by from_trace or by hand
        self.chaos: List[dict] = []
        self._schedule: Optional[List[Arrival]] = None

    @classmethod
    def from_trace(cls, trace) -> "LoadGen":
        """Build a generator that replays a recorded trace instead of
        sampling one: ``trace`` is a path or a dict shaped like
        ``tools/trace_convert.py`` output (or ``trace_bytes()``) —
        ``{"arrivals": [[t, prompt, max_new_tokens, priority], ...]}``
        plus optional ``mode``/``rate``/``duration``/``seed`` metadata
        (nested under ``"meta"`` or top-level). The schedule is
        installed verbatim, so ``run()`` re-fights the recorded
        workload deterministically."""
        if isinstance(trace, (str, os.PathLike)):
            with open(trace) as f:
                trace = json.load(f)
        meta = dict(trace.get("meta") or {})
        for k in ("mode", "rate", "duration", "seed"):
            if k not in meta and k in trace:
                meta[k] = trace[k]
        arrivals = []
        for row in trace["arrivals"]:
            t, prompt, mnt, pri = row[:4]
            extra = ()
            if len(row) > 4:   # decode-bearing rows: 5 more fields
                extra = (float(row[4]), int(row[5]), float(row[6]),
                         int(row[7]), str(row[8]))
            if len(row) > 9:   # abandonment-bearing rows: col 10
                extra = extra + (float(row[9]),)
            if len(row) > 10:  # session-bearing rows: col 11
                extra = extra + (str(row[10]),)
            arrivals.append(Arrival(float(t),
                                    tuple(int(x) for x in prompt),
                                    int(mnt), int(pri), *extra))
        last_t = max((a.t for a in arrivals), default=0.0)
        duration = float(meta.get("duration") or 0.0)
        if duration <= 0:
            # metadata-free trace: synthesize a window covering the
            # recorded arrivals (session follow-up turns legitimately
            # land past the recorded window, so a recorded duration is
            # kept verbatim — byte-identical re-serialization)
            duration = last_t + 1e-6 if arrivals else 1.0
        rate = float(meta.get("rate") or 0.0)
        if rate <= 0:
            rate = max(len(arrivals) / duration, 1e-9)
        mode = meta.get("mode", "poisson")
        if mode not in cls.MODES:   # replayed traces keep MODES closed
            mode = "poisson"
        lg = cls(mode=mode, rate=rate, duration=duration,
                 seed=int(meta.get("seed", 0)))
        lg._schedule = arrivals
        # decode-bearing traces re-serialize with their decode fields
        lg._decoded = any(len(r) > 4 for r in trace["arrivals"])
        # abandonment-bearing traces re-serialize byte-identically too
        lg._abandon = any(len(r) > 9 for r in trace["arrivals"])
        if lg._abandon:
            lg.abandon_frac = 1.0   # marker; the schedule rows govern
        # session-bearing traces re-serialize byte-identically too
        lg._returning = any(len(r) > 10 for r in trace["arrivals"])
        if lg._returning:
            lg.returning_frac = 1.0   # marker; the rows govern
        # chaos rows ([t, kind, index]) replay kill/restart schedules
        lg.chaos = [{"t": float(r[0]), "kind": str(r[1]),
                     "index": int(r[2])}
                    for r in trace.get("chaos", [])]
        return lg

    # ---------------------------------------------------------- schedule
    def _burst_segments(self, rng) -> List[Tuple[float, float]]:
        """Alternating (start_time, rate) segments covering the
        arrival window — the modulating Markov chain, sampled once."""
        segs, t, calm = [], 0.0, True
        while t < self.duration:
            segs.append((t, self.rate if calm
                         else self.rate * self.burst_factor))
            mean = (self.switch_every if calm
                    else self.switch_every * self.burst_fraction)
            t += float(rng.exponential(mean))
            calm = not calm
        return segs

    def _sample_span(self, rng, lo: int, hi: int) -> int:
        """Mixed length distribution: 70% uniform on [lo, mid] (the
        chat-short mode), 30% uniform on [mid, hi] (doc-long)."""
        mid = (lo + hi) // 2
        if rng.uniform() < 0.7:
            return int(rng.randint(lo, mid + 1))
        return int(rng.randint(mid, hi + 1))

    def schedule(self) -> List[Arrival]:
        """The full arrival trace (cached; same seed => same trace).
        Arrivals are generated by thinning a peak-rate Poisson stream,
        consuming the RNG identically whether a candidate is kept or
        thinned — replayability does not depend on acceptance."""
        if self._schedule is not None:
            return self._schedule
        rng = np.random.RandomState(self.seed)
        ab_rng = np.random.RandomState(
            (self.seed * 2654435761 + 131) % (2 ** 32))
        if self.mode == "poisson":
            peak = self.rate
            segs = None
        elif self.mode == "bursty":
            peak = self.rate * self.burst_factor
            segs = self._burst_segments(rng)
        else:  # diurnal
            peak = self.rate * (1.0 + self.diurnal_amplitude)
            segs = None

        def rate_at(t: float) -> float:
            if self.mode == "poisson":
                return self.rate
            if self.mode == "diurnal":
                return self.rate * (1.0 + self.diurnal_amplitude *
                                    math.sin(2.0 * math.pi * t /
                                             self.diurnal_period))
            r = segs[0][1]
            for start, seg_rate in segs:
                if start > t:
                    break
                r = seg_rate
            return r

        out: List[Arrival] = []
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / peak))
            if t >= self.duration:
                break
            keep = float(rng.uniform()) * peak <= rate_at(t)
            plen = self._sample_span(rng, *self.prompt_tokens)
            mnt = self._sample_span(rng, *self.new_tokens)
            prompt = tuple(int(x) for x in
                           rng.randint(1, self.vocab_size, size=plen))
            pri = int(self._pri_vals[int(
                rng.choice(len(self._pri_vals), p=self._pri_probs))])
            extra = ()
            if self._decoded:
                # fixed draw count per candidate (kept or thinned,
                # sampled or greedy) — the same invariant as above
                u = float(rng.uniform())
                temp = round(0.5 + 0.5 * float(rng.uniform()), 3)
                tk = int(rng.choice([0, 8, 16]))
                tp = float(rng.choice([1.0, 0.95, 0.9]))
                sd = int(rng.randint(0, 2 ** 31 - 1))
                if u >= self.sample_frac:
                    temp, tk, tp, sd = 0.0, 0, 1.0, 0
                ten = ""
                if self._tenant_vals:
                    ten = self._tenant_vals[int(rng.choice(
                        len(self._tenant_vals), p=self._tenant_probs))]
                extra = (temp, tk, tp, sd, ten)
            ab = 0.0
            if self._abandon:
                # fixed draw count per candidate (kept or thinned):
                # u1 decides whether this client abandons, u2 picks how
                # far into the token budget it hangs up (25%..75%) —
                # always past the first token, so abandonment lands
                # mid-decode, never pre-admission
                u1 = float(ab_rng.uniform())
                u2 = float(ab_rng.uniform())
                if u1 < self.abandon_frac:
                    ab = round(0.25 + 0.5 * u2, 6)
            if keep:
                out.append(Arrival(round(t, 9), prompt, mnt, pri,
                                   *extra, abandon_after=ab))
        if self._returning and out:
            # A seeded fraction of arrivals open a session: the
            # arrival itself becomes turn 1 and T-1 follow-up turns
            # arrive after idle gaps long enough for the demotion
            # sweep to park the context in the host tier. Every draw
            # comes from this dedicated stream, so returning-free
            # seeds keep their byte-identical traces.
            sess_rng = np.random.RandomState(
                (self.seed * 2654435761 + 163) % (2 ** 32))
            followups: List[Arrival] = []
            sid = 0
            for j, a in enumerate(out):
                if float(sess_rng.uniform()) >= self.returning_frac:
                    continue
                sid += 1
                lo, hi = self.turns_per_session
                turns = int(sess_rng.randint(lo, hi + 1))
                out[j] = a._replace(session=str(sid))
                t = a.t
                for _ in range(turns - 1):
                    gap = float(sess_rng.uniform(0.25, 1.0)) * \
                        max(self.duration, 1e-3)
                    t = t + gap
                    plen = self._sample_span(sess_rng,
                                             *self.prompt_tokens)
                    mnt = self._sample_span(sess_rng,
                                            *self.new_tokens)
                    prompt = tuple(int(x) for x in sess_rng.randint(
                        1, self.vocab_size, size=plen))
                    followups.append(Arrival(
                        round(t, 9), prompt, mnt, a.priority,
                        session=str(sid)))
            out = sorted(out + followups, key=lambda a: a.t)
        self._schedule = out
        return out

    def trace_bytes(self) -> bytes:
        """Canonical JSON of the arrival schedule — the byte-identity
        surface of the determinism contract."""
        rows = []
        for a in self.schedule():
            row = [a.t, list(a.prompt), a.max_new_tokens, a.priority]
            if self._decoded or self._abandon or self._returning:
                # decode-bearing rows carry 5 more; abandonment and
                # session rows pad them (greedy defaults) so col 10
                # stays col 10
                row += [a.temperature, a.top_k, a.top_p, a.seed,
                        a.tenant]
            if self._abandon or self._returning:
                # abandonment-bearing rows add col 10; session rows
                # pad it so col 11 stays col 11
                row.append(a.abandon_after)
            if self._returning:   # session-bearing rows add col 11
                row.append(a.session)
            rows.append(row)
        payload = {
            "mode": self.mode, "rate": self.rate,
            "duration": self.duration, "seed": self.seed,
            "arrivals": rows,
        }
        if self.chaos:   # only chaos-bearing traces grow the key, so
            # chaos-free seeds keep their byte-identical traces
            payload["chaos"] = [
                [e["t"], e["kind"], e["index"]]
                for e in sorted(self.chaos, key=lambda e: e["t"])]
        return json.dumps(payload, sort_keys=True,
                          separators=(",", ":")).encode()

    # --------------------------------------------------------------- run
    @staticmethod
    def _engines(target) -> list:
        engs = getattr(target, "engines", None)
        if engs is None:
            return [target]
        return list(engs) + list(getattr(target, "_retiring", []))

    def run(self, target, clock: Optional[VirtualClock] = None,
            step_cost_ms: float = 0.0,
            slo_ttft_ms: Optional[float] = None,
            include_trace: bool = False,
            max_steps: int = 200_000,
            on_step=None) -> dict:
        """Release the schedule open-loop into ``target`` and drive it
        to drain; returns the report dict.

        With ``clock`` the run is virtual: the target's engines must
        share the same clock (``clock=vc.now`` at construction) and
        each scheduler step advances it ``step_cost_ms``. Without it,
        arrivals ride the wall clock. ``slo_ttft_ms`` sets a post-hoc
        SLO for goodput when the engines run without one (the
        depth-only baseline); engines with their own SLO use their
        deadline verdicts. ``on_step`` (called with the 0-based step
        index after each scheduler step) is the deterministic
        mid-burst hook — hot-swap-under-load tests fire
        ``swap_weights`` from it at an exact step."""
        arrivals = self.schedule()
        records = [{"i": i, "t": a.t, "prompt_tokens": len(a.prompt),
                    "max_new_tokens": a.max_new_tokens,
                    "priority": a.priority,
                    "sampled": a.temperature > 0,
                    "tenant": a.tenant,
                    "abandon_after": a.abandon_after,
                    "session": a.session,
                    "abandoned": False, "outcome": None,
                    "reason": None, "req": None}
                   for i, a in enumerate(arrivals)]
        from paddle_tpu.serving import QueueFullError
        exceptions = 0
        t0 = clock.now() if clock is not None else time.perf_counter()

        def now_s() -> float:
            return ((clock.now() if clock is not None
                     else time.perf_counter()) - t0)

        def release(rec, arr):
            nonlocal exceptions
            kw = {}
            if arr.temperature > 0:   # sampled row: full decode params
                kw.update(temperature=arr.temperature, top_k=arr.top_k,
                          top_p=arr.top_p, seed=arr.seed)
            if arr.tenant:
                kw["tenant"] = arr.tenant
            if arr.session:
                kw["session"] = arr.session
            try:
                rec["req"] = target.submit(
                    list(arr.prompt), max_new_tokens=arr.max_new_tokens,
                    priority=arr.priority, **kw)
                rec["outcome"] = "admitted"
            except QueueFullError as e:
                rec["outcome"] = "rejected"
                rec["reason"] = getattr(e, "reason", "queue_full")
            except ValueError as e:
                rec["outcome"] = "invalid"
                rec["reason"] = str(e)
            except Exception as e:   # graceful degradation: count, go on
                exceptions += 1
                rec["outcome"] = "error"
                rec["reason"] = f"{type(e).__name__}: {e}"

        chaos = sorted(self.chaos, key=lambda e: (e["t"], e["kind"]))
        ci = 0
        chaos_applied = 0

        def fire_chaos():
            nonlocal ci, chaos_applied
            while ci < len(chaos) and chaos[ci]["t"] <= now_s():
                chaos_applied += int(
                    self._apply_chaos(target, chaos[ci]))
                ci += 1

        i, steps = 0, 0
        if self.closed_loop:
            # N clients, each: wait for completion, think (a separate
            # RandomState — the open-loop schedule stream is untouched,
            # so open-loop seeds stay byte-identical), release the next
            # scheduled arrival's content at the loop's own pace
            think_rng = np.random.RandomState(
                (self.seed * 2654435761 + 97) % (2 ** 32))
            lo, hi = self.think_time_ms

            def think_s() -> float:
                return (lo + (hi - lo) *
                        float(think_rng.uniform())) / 1e3

            free_at = [0.0] * self.closed_loop
            pending: List[Optional[dict]] = [None] * self.closed_loop
            while True:
                fire_chaos()
                now = now_s()
                for c in range(self.closed_loop):
                    rec = pending[c]
                    if rec is not None:
                        req = rec["req"]
                        if req is not None and \
                                req.state not in ("done", "shed",
                                                  "canceled"):
                            # impatient client: once enough of the
                            # token budget has landed, hang up — a
                            # fleet-wide cancel that must reclaim every
                            # block (the abandonment workload)
                            if rec["abandon_after"] > 0 and \
                                    not rec["abandoned"] and \
                                    req.first_token_at is not None and \
                                    len(req.tokens) >= max(1, math.ceil(
                                        rec["abandon_after"] *
                                        rec["max_new_tokens"])):
                                rec["abandoned"] = True
                                target.cancel(req.id,
                                              reason="disconnect")
                            continue
                        done_at = now
                        if req is not None and \
                                req.finished_at is not None:
                            done_at = max(0.0, req.finished_at - t0)
                        free_at[c] = done_at + think_s()
                        pending[c] = None
                    if i < len(arrivals) and free_at[c] <= now:
                        rec = records[i]
                        rec["t"] = round(now, 9)  # actual release time
                        release(rec, arrivals[i])
                        i += 1
                        if rec["outcome"] == "admitted":
                            pending[c] = rec
                        else:
                            free_at[c] = now + think_s()
                if i >= len(arrivals) and target.idle and \
                        all(p is None for p in pending):
                    break
                if target.idle:
                    nxt = min((free_at[c]
                               for c in range(self.closed_loop)
                               if pending[c] is None), default=now)
                    gap = nxt - now
                    if gap > 0:
                        if clock is not None:
                            clock.advance(gap)
                        else:
                            time.sleep(min(gap, 0.05))
                    continue
                target.step()
                if on_step is not None:
                    on_step(steps)
                if clock is not None:
                    clock.advance(step_cost_ms / 1e3)
                steps += 1
                if steps >= max_steps:
                    raise RuntimeError(
                        f"loadgen target not drained after "
                        f"{max_steps} steps")
        else:
            while i < len(arrivals) or not target.idle:
                fire_chaos()
                while i < len(arrivals) and arrivals[i].t <= now_s():
                    release(records[i], arrivals[i])
                    i += 1
                if target.idle:
                    if i >= len(arrivals):
                        break
                    gap = arrivals[i].t - now_s()
                    if clock is not None:
                        clock.advance(max(0.0, gap))
                    else:
                        time.sleep(min(max(gap, 0.0), 0.05))
                    continue
                target.step()
                if on_step is not None:
                    on_step(steps)
                if clock is not None:
                    clock.advance(step_cost_ms / 1e3)
                steps += 1
                if steps >= max_steps:
                    raise RuntimeError(
                        f"loadgen target not drained after "
                        f"{max_steps} steps")
        makespan = max(now_s(), 1e-9)
        return self._report(records, makespan, steps, slo_ttft_ms,
                            target, exceptions, include_trace,
                            t0=t0, chaos_applied=chaos_applied)

    @staticmethod
    def _apply_chaos(target, ev: dict) -> bool:
        """Fire one recorded chaos event against the target; returns
        whether it applied. A fleet whose shape diverged from the
        recording (fewer replicas, different roles) skips events it
        cannot map rather than crashing the replay."""
        kind, idx = ev["kind"], int(ev["index"])
        try:
            if kind == "restart":
                target.restart_replica(idx)
            elif kind == "kill":
                target.kill_replica(idx)
            elif kind == "kill_decode":
                target.kill_decode_worker(idx)
            elif kind == "kill_prefill":
                target.kill_prefill_worker(idx)
            else:
                return False
        except (AttributeError, IndexError, ValueError):
            return False
        return True

    def _report(self, records, makespan, steps, slo_ttft_ms, target,
                exceptions, include_trace, t0: float = 0.0,
                chaos_applied: int = 0) -> dict:
        shed: dict = {}
        canceled: dict = {}
        abandoned = 0
        decisions: List[List] = []
        ttfts, tpots = [], []
        completed = rehomed_done = slo_met = slo_known = 0
        per_tenant: dict = {}
        for rec in records:
            tstats = per_tenant.setdefault(
                rec["tenant"] or "base",
                {"offered": 0, "completed": 0, "sampled": 0,
                 "slo_met": 0, "_slo_known": 0})
            tstats["offered"] += 1
            tstats["sampled"] += int(rec["sampled"])
            req = rec.pop("req")
            if req is not None:
                rec["outcome"] = ("done" if req.state == "done"
                                  else req.state)
                rec["reason"] = req.shed_reason
                rec["ttft_ms"] = (None if req.ttft is None
                                  else round(req.ttft * 1e3, 3))
                rec["tpot_ms"] = (None if req.tpot is None
                                  else round(req.tpot * 1e3, 3))
                rec["rehomed"] = bool(getattr(req, "rehomed", False))
                rec["done_t"] = (
                    None if req.finished_at is None
                    else round(max(0.0, req.finished_at - t0), 6))
                met = req.deadline_met
                if met is None and slo_ttft_ms and req.ttft is not None:
                    met = req.ttft * 1e3 <= slo_ttft_ms
                rec["deadline_met"] = met
                if req.state == "done":
                    # a re-homed completion lands in its own bucket so
                    # completed + shed + rehomed == offered (modulo
                    # rejects/errors) survives a kill; its latency and
                    # SLO verdict still count below — recovered work
                    # is goodput
                    if rec["rehomed"]:
                        rehomed_done += 1
                    else:
                        completed += 1
                    tstats["completed"] += 1
                    if req.ttft is not None:
                        ttfts.append(req.ttft * 1e3)
                    if req.tpot is not None:
                        tpots.append(req.tpot * 1e3)
                    if met is not None:
                        slo_known += 1
                        slo_met += int(met)
                        tstats["_slo_known"] += 1
                        tstats["slo_met"] += int(met)
            if rec["outcome"] in ("shed", "rejected"):
                key = rec["reason"] or "unknown"
                shed[key] = shed.get(key, 0) + 1
            elif rec["outcome"] == "canceled":
                key = rec["reason"] or "unknown"
                canceled[key] = canceled.get(key, 0) + 1
            abandoned += int(rec["abandoned"])
            decisions.append([rec["outcome"], rec.get("reason")])

        leaked = 0
        seen_allocs = set()   # co-located disagg roles share one pool
        for eng in self._engines(target):
            if getattr(eng, "paged", False):
                alloc = eng.cache.allocator
                if id(alloc) in seen_allocs:
                    continue
                seen_allocs.add(id(alloc))
                eng.cache.flush_prefix_cache()
                leaked += max(0, alloc.leaked() - 1)

        def pct(vals, q):
            return (round(float(np.percentile(vals, q)), 3)
                    if vals else None)

        engine_slo = next((e.slo_ttft_ms
                           for e in self._engines(target)
                           if e.slo_ttft_ms), 0.0)
        report = {
            "mode": self.mode, "seed": self.seed, "rate": self.rate,
            "duration_s": self.duration,
            "offered": len(records),
            "offered_rate": round(len(records) / self.duration, 3),
            "makespan_s": round(makespan, 6),
            "steps": steps,
            "admitted": sum(1 for d in decisions
                            if d[0] in ("done", "shed", "canceled")),
            "completed": completed,
            "rehomed": rehomed_done,
            "canceled": canceled,
            "canceled_total": sum(canceled.values()),
            "abandoned": abandoned,
            "closed_loop": self.closed_loop,
            "chaos_applied": chaos_applied,
            "shed": shed,
            "shed_total": sum(shed.values()),
            "exceptions": exceptions,
            "slo_ttft_ms": engine_slo or slo_ttft_ms or None,
            "slo_met": slo_met if slo_known else None,
            "slo_attainment": (round(slo_met / slo_known, 4)
                               if slo_known else None),
            "goodput_per_s": (round(slo_met / makespan, 4)
                              if slo_known else None),
            "throughput_per_s": round(
                (completed + rehomed_done) / makespan, 4),
            "ttft_ms_p50": pct(ttfts, 50),
            "ttft_ms_p95": pct(ttfts, 95),
            "ttft_ms_p99": pct(ttfts, 99),
            "tpot_ms_p50": pct(tpots, 50),
            "tpot_ms_p95": pct(tpots, 95),
            "tpot_ms_p99": pct(tpots, 99),
            "leaked_kv_blocks": leaked,
            "decisions": decisions,
        }
        if self._decoded:
            # per-tenant goodput: who got served, who met the SLO,
            # straight from the loadgen's own records (the target's
            # stats()["tenants"] view must agree — CI cross-checks)
            for name, ts in per_tenant.items():
                known = ts.pop("_slo_known")
                ts["slo_attainment"] = (round(ts["slo_met"] / known, 4)
                                        if known else None)
                ts["goodput_per_s"] = (round(ts["slo_met"] / makespan, 4)
                                       if known else None)
            report["per_tenant"] = dict(sorted(per_tenant.items()))
            leaked_pages = 0
            seen_pools = set()
            for eng in self._engines(target):
                pool = getattr(eng, "lora_pool", None)
                if pool is not None and id(pool) not in seen_pools:
                    seen_pools.add(id(pool))
                    leaked_pages += pool.leaked()
            report["leaked_lora_pages"] = leaked_pages
        if self._returning:
            # returning-users section: session volume straight from
            # the records, residency/migration/resume accounting from
            # the fleet-shared tier, and the zero-leak identity for
            # the host half (flush first, like the device pools above)
            tier = next(
                (e.kv_tier for e in self._engines(target)
                 if getattr(e, "kv_tier", None) is not None), None)
            sess: dict = {
                "sessions_offered": len({r["session"] for r in records
                                         if r["session"]}),
                "session_turns": sum(1 for r in records
                                     if r["session"]),
            }
            dev_blocks = next(
                (e.cache.allocator.num_blocks
                 for e in self._engines(target)
                 if getattr(e, "paged", False)), 0)
            sess["device_blocks"] = dev_blocks
            if tier is not None:
                ts = tier.stats()
                sess.update(
                    sessions_resumed=ts["sessions_resumed"],
                    sessions_peak=ts["sessions_peak"],
                    host_blocks=ts["host_blocks"],
                    host_blocks_peak=ts["host_blocks_peak"],
                    host_evictions=ts["host_evictions"],
                    migrated_demote_blocks=ts["migrated_demote_blocks"],
                    migrated_promote_blocks=ts[
                        "migrated_promote_blocks"],
                    demote_dedup_entries=ts["demote_dedup_entries"])
                tier.flush()
                sess["leaked_host_blocks"] = tier.leaked()
            report["sessions"] = sess
        stats = getattr(target, "stats", None)
        st = stats() if callable(stats) else {}
        if "hedges" in st:
            # hedged-prefill section: volume (rate vs offered, budget
            # tokens left), outcome split, and the duplicated-token
            # cost of racing — the ISSUE-locked report surface
            h = dict(st["hedges"])
            fired = int(h.get("fired", 0))
            h["hedge_rate"] = round(fired / max(1, len(records)), 4)
            h["win_rate"] = (round(int(h.get("wins", 0)) / fired, 4)
                             if fired else None)
            report["hedges"] = h
        if "devprof" in st:
            # device-cost observatory section: sampled device/host
            # split, per-entry rooflines/MFU — informational on wall
            # clocks, deterministic zeros on a VirtualClock run (the
            # perf ledger stores it alongside the goodput numbers)
            report["devprof"] = st["devprof"]
        if "prefill_workers" in st:
            report["disagg"] = {k: st[k] for k in (
                "prefill_workers", "decode_workers", "colocated",
                "handoffs_adopted", "handoffs_copied", "prefix_affinity",
                "affinity_hits", "affinity_misses",
                "fleet_prefix_hit_rate")}
        if include_trace:
            report["trace"] = records
        return report


def warmup(target, max_new_tokens: int = 2):
    """Pay the XLA compiles before any measured/admission-bearing
    traffic: one request per prefill bucket plus the decode step, run
    to idle, then drop each engine's learned cost EWMAs so predictions
    reflect steady-state dispatch costs, not trace time."""
    from paddle_tpu.serving import QueueFullError
    engines = LoadGen._engines(target)
    eng = engines[0]
    for b in eng.buckets:
        plen = max(1, min(b, eng.max_len - max_new_tokens -
                          eng.spec_tokens))
        for _ in range(50):   # ride out injected submit faults
            try:
                # warmup traffic stays out of the runlog so replayable
                # traces (tools/trace_convert.py) carry only the
                # measured workload
                target.submit([1] * plen,
                              max_new_tokens=max_new_tokens,
                              _log_request=False)
                break
            except QueueFullError:
                target.run_until_idle()
    target.run_until_idle()
    tiers = set()
    for e in engines:
        e.reset_cost_estimates()
        if e.paged:
            e.cache.flush_prefix_cache()
        # warmup chains demoted by the between-steps sweep would sit
        # in the (fleet-shared) host store; flush it once so measured
        # traffic starts from an empty tier
        tier = getattr(e, "kv_tier", None)
        if tier is not None and id(tier) not in tiers:
            tiers.add(id(tier))
            tier.flush()


# ------------------------------------------------------------------ CLI
def _parse_range(text: str) -> Tuple[int, int]:
    lo, hi = (int(p) for p in str(text).split(":"))
    return lo, hi


def _parse_frange(text: str) -> Tuple[float, float]:
    lo, hi = (float(p) for p in str(text).split(":"))
    return lo, hi


def _parse_mix(text: str) -> Optional[dict]:
    if not text:
        return None
    out = {}
    for part in text.split(","):
        k, v = part.split(":")
        out[int(k)] = float(v)
    return out


def _parse_tenant_mix(text: str) -> Optional[dict]:
    if not text:
        return None
    out = {}
    for part in text.split(","):
        k, v = part.split(":")
        out[str(k)] = float(v)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop load generator for the serving plane")
    ap.add_argument("--mode", default="poisson",
                    choices=list(LoadGen.MODES))
    ap.add_argument("--rate", type=float, default=8.0,
                    help="calm/mean arrival rate, requests/s")
    ap.add_argument("--duration", type=float, default=4.0,
                    help="arrival window, seconds")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--model", default="gpt2-tiny",
                    help="GPT_CONFIGS name")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--buckets", default="16,32,64",
                    help="comma-separated prefill buckets")
    ap.add_argument("--prompt-tokens", type=_parse_range, default=(4, 24),
                    metavar="LO:HI")
    ap.add_argument("--new-tokens", type=_parse_range, default=(2, 16),
                    metavar="LO:HI")
    ap.add_argument("--closed-loop", type=int, default=0,
                    metavar="N", help="> 0 runs N closed-loop clients "
                    "(each waits for completion + think time before "
                    "re-submitting) instead of open-loop release")
    ap.add_argument("--think-time-ms", type=_parse_frange,
                    default=(0.0, 0.0), metavar="A:B",
                    help="closed-loop per-client think time, uniform "
                    "on [A, B] ms from a dedicated seeded stream")
    ap.add_argument("--abandon-frac", type=float, default=0.0,
                    metavar="F", help="fraction of closed-loop clients "
                    "that hang up mid-decode (seeded draws from a "
                    "dedicated stream; each fires a fleet cancel once "
                    "25-75%% of its token budget has landed); "
                    "requires --closed-loop")
    ap.add_argument("--returning-frac", type=float, default=0.0,
                    metavar="F", help="fraction of arrivals that open "
                    "a multi-turn session (seeded draws from a "
                    "dedicated stream): follow-up turns arrive after "
                    "idle gaps and submit with session=<id> so the "
                    "host KV tier resumes the stored context; "
                    "requires --host-blocks")
    ap.add_argument("--turns-per-session", type=_parse_range,
                    default=(2, 4), metavar="A:B",
                    help="returning-users turns per session, uniform "
                    "on [A, B] from the session stream")
    ap.add_argument("--host-blocks", type=int, default=0,
                    metavar="N", help="> 0 turns on the host-RAM KV "
                    "tier (FLAGS_serving_host_tier) with N host "
                    "blocks — cold chains demote int8-at-rest and "
                    "sessions park/resume through the fleet-shared "
                    "store")
    ap.add_argument("--demote-idle-ms", type=float, default=None,
                    metavar="MS", help="FLAGS_serving_demote_idle_ms "
                    "for the run: how long (engine clock) a prefix "
                    "entry must sit cold before the sweep demotes it "
                    "(0 = every step; default: the flag)")
    ap.add_argument("--priority-mix", type=_parse_mix, default=None,
                    metavar="P:W,P:W", help="priority class weights, "
                    "e.g. '0:0.1,1:0.8,2:0.1' (lower = more urgent)")
    ap.add_argument("--sample-frac", type=float, default=0.0,
                    help="fraction of arrivals carrying sampled decode "
                    "params (seeded temperature/top-k/top-p); the rest "
                    "stay greedy")
    ap.add_argument("--tenant-mix", type=_parse_tenant_mix,
                    default=None, metavar="NAME:W,NAME:W",
                    help="multi-tenant LoRA mix, e.g. "
                    "'base:0.5,acme:0.3,zeta:0.2' ('base' = no "
                    "adapter); non-base tenants need --lora-rank")
    ap.add_argument("--lora-rank", type=int, default=0,
                    help="> 0 builds the paged LoRA adapter pool "
                    "(FLAGS_serving_lora_rank) and loads one seeded "
                    "adapter per non-base tenant in --tenant-mix")
    ap.add_argument("--slo-ttft-ms", type=float, default=0.0,
                    help="> 0 turns on SLO-aware admission; also the "
                    "goodput SLO for reporting")
    ap.add_argument("--slo-prefill-ms", type=float, default=0.0,
                    help="pin the predictor's prefill cost (0 = EWMA)")
    ap.add_argument("--slo-tpot-ms", type=float, default=0.0,
                    help="pin the predictor's per-token cost (0 = EWMA)")
    ap.add_argument("--depth-only", action="store_true",
                    help="run the engine WITHOUT SLO admission but "
                    "still score goodput against --slo-ttft-ms "
                    "(the baseline arm of the bench)")
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--autoscale", default="", metavar="MIN:MAX",
                    help="enable router autoscaling inside the bounds")
    ap.add_argument("--hedge-ms", type=float, default=0.0,
                    metavar="MS", help="router hedged prefill: when "
                    "a request's predicted TTFT exceeds MS, race a "
                    "clone on the second-best replica after that "
                    "delay (> 0 fixed threshold, -1 auto from the "
                    "traced TTFT p95, 0 off); adds a 'hedges' report "
                    "section")
    ap.add_argument("--hedge-budget", type=float, default=None,
                    metavar="FRAC", help="hedge token bucket refill "
                    "per offered request (fired hedges <= 1 + "
                    "FRAC * offered; default "
                    "FLAGS_serving_hedge_budget)")
    ap.add_argument("--straggler", default="", metavar="I:MS",
                    help="after warmup, pin replica I's predicted "
                    "prefill cost to MS ms and slow its steps to "
                    "match — the deterministic straggler the hedge "
                    "races against (wall-clock multi-replica runs)")
    ap.add_argument("--disagg", default="", metavar="PxD",
                    help="run a disaggregated fleet of P prefill-only "
                    "+ D decode-only workers behind a DisaggRouter "
                    "instead of symmetric replicas (e.g. '1x2')")
    ap.add_argument("--no-prefix-affinity", action="store_true",
                    help="with --disagg: route least-loaded instead of "
                    "to the worker holding the longest cached prefix")
    ap.add_argument("--chaos", default="", metavar="T:KIND:I,...",
                    help="inline chaos schedule fired on the run "
                    "clock: comma-separated T:KIND:INDEX events, KIND "
                    "in kill|restart|kill_decode|kill_prefill (e.g. "
                    "'2.0:kill:0' kills replica 0 two seconds in)")
    ap.add_argument("--replay", default="", metavar="TRACE.json",
                    help="replay a recorded arrival trace (from "
                    "tools/trace_convert.py or a prior --trace file) "
                    "instead of sampling a schedule")
    ap.add_argument("--megastep", type=int, default=1, metavar="N",
                    help="> 1 runs device-resident decode megasteps "
                    "(FLAGS_serving_megastep): N decode iterations per "
                    "compiled dispatch with one host commit per "
                    "megastep; tokens are byte-identical to N=1. "
                    "Plumbs through engines, replica routers and "
                    "disagg decode workers alike")
    ap.add_argument("--dispatch-ahead", action="store_true",
                    help="with --megastep > 1: enqueue megastep k+1 "
                    "while k executes (FLAGS_serving_dispatch_ahead); "
                    "the host commit validates the speculation and "
                    "discards it on any roster/sampling change")
    ap.add_argument("--dispatch-threads", type=int, default=0,
                    metavar="T", help="> 0 steps router replicas / "
                    "disagg workers from a bounded pool of T threads "
                    "(FLAGS_serving_dispatch_threads); 0 keeps the "
                    "serial byte-identical loop")
    ap.add_argument("--virtual-step-ms", type=float, default=0.0,
                    help="> 0 runs on a virtual clock advancing this "
                    "much per step (fully deterministic replay)")
    ap.add_argument("--fault-spec", default="",
                    help="chaos crossover: FLAGS_fault_spec for the run "
                    "(e.g. 'serving.submit:skip@0.2;serving.alloc:"
                    "skip@0.2')")
    ap.add_argument("--fault-seed", type=int, default=0)
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="print the report as one JSON line")
    ap.add_argument("--trace", default="",
                    help="write the per-request trace JSON here")
    ap.add_argument("--trace-sample", type=float, default=None,
                    metavar="FRAC",
                    help="FLAGS_serving_trace for this run: fraction "
                    "of requests carrying a distributed trace "
                    "(deterministic id-hash sampling; 1.0 = all, "
                    "0 = off). Host-side only — zero new compiles")
    ap.add_argument("--devprof", action="store_true",
                    help="turn on the device-cost observatory "
                         "(FLAGS_serving_devprof) for every engine "
                         "this run constructs: XLA cost capture, "
                         "sampled device timing, roofline gauges, "
                         "decode blame split")
    ap.add_argument("--devprof-sample", type=float, default=None,
                    metavar="FRAC",
                    help="override FLAGS_serving_devprof_sample "
                         "(fraction of step dispatches that pay a "
                         "block_until_ready timer; default keeps the "
                         "flag's 0.1)")
    ap.add_argument("--ledger", default="", metavar="PATH",
                    help="append this run's headline metrics (+ "
                         "devprof roofline summary and cost digest) "
                         "as one tools/perf_ledger.py JSONL row")
    ap.add_argument("--span-trace-out", default="", metavar="PATH",
                    help="export the sampled requests' span traces as "
                    "Perfetto-loadable chrome-trace JSON after the run")
    ap.add_argument("--expect-goodput-min", type=float, default=None,
                    help="exit 1 unless goodput_per_s >= this")
    ap.add_argument("--expect-zero-leaks", action="store_true",
                    help="exit 1 unless leaked_kv_blocks == 0 (and "
                    "leaked_lora_pages == 0 when LoRA is on)")
    ap.add_argument("--expect-zero-new-compiles", action="store_true",
                    help="exit 1 if any serving/decode/verify step "
                    "compiled after warmup — the sampling-as-data / "
                    "paged-LoRA contract under mixed traffic")
    ap.add_argument("--expect-sheds-min", type=int, default=None,
                    help="exit 1 unless shed_total >= this (chaos runs "
                    "must actually shed)")
    ap.add_argument("--expect-resumed-min", type=int, default=None,
                    help="exit 1 unless sessions_resumed >= this "
                    "(returning-users runs must actually resume)")
    ap.add_argument("--expect-capacity-gt-device",
                    action="store_true",
                    help="exit 1 unless the peak concurrent-session "
                    "count exceeds the device pool's block count — "
                    "the sessions-beyond-HBM capacity gate (host "
                    "tier on)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPT_CONFIGS, GPTForCausalLM
    from paddle_tpu.resilience import fault_scope
    from paddle_tpu.serving import AutoscalePolicy, ReplicaRouter, \
        ServingEngine
    from paddle_tpu.serving.router import _parse_autoscale

    from contextlib import nullcontext
    ctx = (fault_scope(args.fault_spec, seed=args.fault_seed)
           if args.fault_spec else nullcontext())
    cfg = GPT_CONFIGS[args.model]
    pt.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    if args.abandon_frac and not args.closed_loop:
        print("FAIL: --abandon-frac needs --closed-loop clients "
              "(abandonment is a client hang-up mid-decode)",
              file=sys.stderr)
        return 1
    if args.returning_frac and args.host_blocks <= 0:
        print("FAIL: --returning-frac needs --host-blocks > 0 "
              "(session resume parks context in the host KV tier)",
              file=sys.stderr)
        return 1
    if args.host_blocks > 0:
        from paddle_tpu import flags as _fl
        tier_flags = {"serving_host_tier": True,
                      "serving_host_blocks": args.host_blocks}
        if args.demote_idle_ms is not None:
            tier_flags["serving_demote_idle_ms"] = args.demote_idle_ms
        _fl.set_flags(tier_flags)
    if args.replay:
        lg = LoadGen.from_trace(args.replay)
        if args.closed_loop:
            lg.closed_loop = int(args.closed_loop)
            lg.think_time_ms = args.think_time_ms
    else:
        lg = LoadGen(mode=args.mode, rate=args.rate,
                     duration=args.duration, seed=args.seed,
                     vocab_size=cfg.vocab_size,
                     prompt_tokens=args.prompt_tokens,
                     new_tokens=args.new_tokens,
                     priority_mix=args.priority_mix,
                     sample_frac=args.sample_frac,
                     tenant_mix=args.tenant_mix,
                     closed_loop=args.closed_loop,
                     think_time_ms=args.think_time_ms,
                     abandon_frac=args.abandon_frac,
                     returning_frac=args.returning_frac,
                     turns_per_session=args.turns_per_session)
    if args.chaos:
        for part in args.chaos.split(","):
            t_s, kind, idx = part.split(":")
            lg.chaos.append({"t": float(t_s), "kind": str(kind),
                             "index": int(idx)})
        lg.chaos.sort(key=lambda e: e["t"])
    lora_tenants = sorted(t for t in (args.tenant_mix or {})
                          if t not in ("", "base"))
    if lora_tenants and args.lora_rank <= 0:
        print("FAIL: --tenant-mix names non-base tenants; they need "
              "--lora-rank > 0", file=sys.stderr)
        return 1
    if args.lora_rank > 0:
        from paddle_tpu import flags as _fl
        _fl.set_flags({"serving_lora_rank": args.lora_rank,
                       "serving_lora_max_adapters":
                           max(len(lora_tenants), 1)})
    if args.megastep < 1:
        print("FAIL: --megastep must be >= 1", file=sys.stderr)
        return 1
    if args.dispatch_ahead and args.megastep <= 1:
        print("FAIL: --dispatch-ahead needs --megastep > 1",
              file=sys.stderr)
        return 1
    if args.dispatch_threads < 0:
        print("FAIL: --dispatch-threads must be >= 0", file=sys.stderr)
        return 1
    if args.megastep > 1 or args.dispatch_threads > 0:
        # one flag write covers every construction path below: engines
        # built directly, inside ReplicaRouter, and inside DisaggRouter
        # decode workers all read these flags when no kwarg overrides
        from paddle_tpu import flags as _fl
        _fl.set_flags({
            "serving_megastep": args.megastep,
            "serving_dispatch_ahead": bool(args.dispatch_ahead),
            "serving_dispatch_threads": args.dispatch_threads})
    if args.trace_sample is not None:
        from paddle_tpu import flags as _fl
        _fl.set_flags({"serving_trace": args.trace_sample})
    if args.devprof_sample is not None and not args.devprof:
        print("FAIL: --devprof-sample needs --devprof",
              file=sys.stderr)
        return 1
    if args.devprof:
        # flag write (not an engine kwarg) so router- and
        # disagg-constructed engines profile too
        from paddle_tpu import flags as _fl
        dp_flags = {"serving_devprof": True}
        if args.devprof_sample is not None:
            dp_flags["serving_devprof_sample"] = args.devprof_sample
        _fl.set_flags(dp_flags)
    from paddle_tpu.observability import tracing as _tracing
    _tracing.reset()
    vc = (VirtualClock() if args.virtual_step_ms > 0 else None)
    eng_kwargs = dict(
        max_slots=args.slots, max_len=args.max_len,
        max_queue=args.max_queue,
        buckets=[int(b) for b in args.buckets.split(",")],
        slo_ttft_ms=0.0 if args.depth_only else args.slo_ttft_ms,
        slo_prefill_ms=args.slo_prefill_ms,
        slo_tpot_ms=args.slo_tpot_ms)
    if vc is not None:
        eng_kwargs["clock"] = vc.now
    with ctx:
        bounds = _parse_autoscale(args.autoscale)
        if args.disagg:
            from paddle_tpu import flags as _fl
            from paddle_tpu.serving import DisaggRouter
            _fl.set_flags({
                "serving_disagg": args.disagg,
                "serving_prefix_affinity":
                    not args.no_prefix_affinity})
            target = DisaggRouter(model=model, **eng_kwargs)
        elif args.replicas > 1 or bounds is not None or \
                args.hedge_ms != 0.0:
            target = ReplicaRouter(
                model=model, n_replicas=args.replicas,
                autoscale=(None if bounds is None else AutoscalePolicy(
                    min_replicas=bounds[0], max_replicas=bounds[1])),
                hedge_ms=args.hedge_ms,
                hedge_budget=args.hedge_budget,
                **eng_kwargs)
        else:
            target = ServingEngine(model, **eng_kwargs)
        if lora_tenants:
            # one seeded adapter per named tenant, loaded before any
            # traffic — a pure pool write, zero new compiles
            from paddle_tpu.serving import make_adapter
            for i, name in enumerate(lora_tenants):
                target.load_adapter(
                    name, make_adapter(cfg, args.lora_rank, seed=i + 1))
        if not args.no_warmup:
            warmup(target)
        if args.straggler:
            # deterministic straggler: pin one replica's predicted
            # prefill cost high (so the hedge gate sees it coming) and
            # stretch each real step to MS of wall time spread over
            # three router passes (two idle passes of MS/3, then the
            # real step). Spreading matters twice over: hedge-fire
            # checks run between router passes, so a sleep-then-step
            # wrapper would finish the prefill inside the very pass
            # that slept and beat every hedge — and the strikes
            # watchdog kills a replica after three consecutive
            # unproductive passes while it holds work, so the wrapper
            # must produce every third call to stay the slow-but-
            # *alive* tail replica hedging exists for, not a dead one.
            # Applied after warmup: pins survive reset_cost_estimates
            # and the wrapper compiles nothing.
            si_s, sms_s = args.straggler.split(":")
            si, sms = int(si_s), float(sms_s)
            slow_eng = target.engines[si]
            slow_eng._prefill_ms_pin = sms
            _orig_step = slow_eng.step
            _stall = {"n": 0}

            def _slow_step(_o=_orig_step, _ms=sms):
                time.sleep(_ms / 3e3)
                _stall["n"] += 1
                if _stall["n"] % 3:
                    return False
                return _o()
            slow_eng.step = _slow_step
        from paddle_tpu import observability as _obs
        _SERVING = ("serving_", "decode_", "verify_")
        base_compiles = {k: v["count"] for k, v in _obs.compiles().items()
                        if k.startswith(_SERVING)}
        report = lg.run(target, clock=vc,
                        step_cost_ms=args.virtual_step_ms,
                        slo_ttft_ms=args.slo_ttft_ms or None,
                        include_trace=bool(args.trace))
        report["new_compiles_after_warmup"] = sum(
            v["count"] - base_compiles.get(k, 0)
            for k, v in _obs.compiles().items()
            if k.startswith(_SERVING))
    trace = report.pop("trace", None)
    if args.trace:
        with open(args.trace, "w") as f:
            json.dump({"schedule": json.loads(lg.trace_bytes()),
                       "requests": trace}, f)
    if args.span_trace_out:
        _tracing.export_chrome_trace(args.span_trace_out)
        report["span_trace"] = args.span_trace_out
    # blame rides in the report whenever any request carried a trace
    # (FLAGS_serving_trace defaults to sampling everything)
    blame = _tracing.blame_summary()
    if blame["requests"]:
        report["blame"] = blame
    if args.ledger:
        from tools import perf_ledger
        report["ledger_row"] = perf_ledger.append_report(
            args.ledger, report, run="loadgen")
    if args.json:
        print(json.dumps(report))
    else:
        for k, v in report.items():
            if k != "decisions":
                print(f"{k}: {v}")
    ok = True
    if args.expect_goodput_min is not None:
        g = report["goodput_per_s"]
        if g is None or g < args.expect_goodput_min:
            print(f"FAIL: goodput_per_s {g} < "
                  f"{args.expect_goodput_min}", file=sys.stderr)
            ok = False
    if args.expect_zero_leaks and report["leaked_kv_blocks"] != 0:
        print(f"FAIL: leaked_kv_blocks = "
              f"{report['leaked_kv_blocks']}", file=sys.stderr)
        ok = False
    if args.expect_zero_leaks and report.get("leaked_lora_pages"):
        print(f"FAIL: leaked_lora_pages = "
              f"{report['leaked_lora_pages']}", file=sys.stderr)
        ok = False
    if args.expect_zero_new_compiles and \
            report["new_compiles_after_warmup"] != 0:
        print(f"FAIL: new_compiles_after_warmup = "
              f"{report['new_compiles_after_warmup']}", file=sys.stderr)
        ok = False
    if args.expect_sheds_min is not None and \
            report["shed_total"] < args.expect_sheds_min:
        print(f"FAIL: shed_total {report['shed_total']} < "
              f"{args.expect_sheds_min}", file=sys.stderr)
        ok = False
    sess = report.get("sessions", {})
    if args.expect_resumed_min is not None:
        r = sess.get("sessions_resumed")
        if r is None or r < args.expect_resumed_min:
            print(f"FAIL: sessions_resumed {r} < "
                  f"{args.expect_resumed_min}", file=sys.stderr)
            ok = False
    if args.expect_capacity_gt_device:
        peak, dev = sess.get("sessions_peak"), sess.get(
            "device_blocks", 0)
        if peak is None or peak <= dev:
            print(f"FAIL: sessions_peak {peak} <= device_blocks "
                  f"{dev} (no capacity win over HBM)", file=sys.stderr)
            ok = False
    if args.expect_zero_leaks and sess.get("leaked_host_blocks"):
        print(f"FAIL: leaked_host_blocks = "
              f"{sess['leaked_host_blocks']}", file=sys.stderr)
        ok = False
    if report["exceptions"]:
        print(f"FAIL: {report['exceptions']} unhandled exceptions",
              file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

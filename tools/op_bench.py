#!/usr/bin/env python
"""Per-op microbenchmark CLI — the op_tester harness.

Analog of paddle/fluid/operators/benchmark/op_tester.cc (config-driven
single-op benchmark). Usage:

    python tools/op_bench.py --op matmul_v2 \
        --input 'X:4096x4096:float32' --input 'Y:4096x4096:float32' \
        --attr transpose_y=false --repeat 50

Runs the registered lowering under jit on the default backend (the real
TPU chip under axon), synchronizing by fetch, and prints one JSON line
with mean/min step time and achieved GFLOP/s when --flops is given.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _parse_input(spec: str):
    name, shape_s, dtype = (spec.split(":") + ["float32"])[:3]
    shape = tuple(int(d) for d in shape_s.split("x"))
    return name, shape, dtype


def _parse_attr(spec: str):
    k, _, v = spec.partition("=")
    try:
        return k, json.loads(v)  # numbers, bools, lists, dicts
    except (json.JSONDecodeError, ValueError):
        return k, v


def main(argv=None):
    p = argparse.ArgumentParser("op_bench")
    p.add_argument("--op", required=True)
    p.add_argument("--input", action="append", default=[],
                   help="slot:shape:dtype, e.g. X:128x1024:float32 "
                        "(slot[i] for list slots: X0,X1 -> slot X)")
    p.add_argument("--attr", action="append", default=[])
    p.add_argument("--repeat", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--flops", type=float, default=0.0,
                   help="analytic FLOPs per call (for GFLOP/s)")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import registry as reg

    rng = np.random.RandomState(0)
    ins = {}
    for spec in args.input:
        name, shape, dtype = _parse_input(spec)
        slot = name.rstrip("0123456789") or name
        arr = (rng.randint(0, 1000, shape).astype(dtype)
               if np.issubdtype(np.dtype(dtype), np.integer)
               else rng.randn(*shape).astype(dtype))
        ins.setdefault(slot, []).append(jnp.asarray(arr))
    attrs = dict(_parse_attr(a) for a in args.attr)

    def run(arrs):
        ctx = reg.LoweringContext(rng=jax.random.PRNGKey(0))
        outs = reg.execute(ctx, args.op, arrs, attrs)
        return [v for vals in outs.values() for v in vals
                if hasattr(v, "dtype")]

    fn = jax.jit(run)
    for _ in range(args.warmup):
        out = fn(ins)
        np.asarray(out[0])  # fetch-sync (tunnel-safe)
    times = []
    for _ in range(args.repeat):
        t0 = time.perf_counter()
        out = fn(ins)
        np.asarray(out[0])
        times.append(time.perf_counter() - t0)
    mean_s, min_s = float(np.mean(times)), float(np.min(times))
    result = {
        "op": args.op,
        "mean_ms": round(mean_s * 1e3, 4),
        "min_ms": round(min_s * 1e3, 4),
        "repeat": args.repeat,
        "backend": jax.default_backend(),
    }
    if args.flops:
        result["gflops"] = round(args.flops / min_s / 1e9, 6)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Measure Hogwild device workers (train_from_dataset thread_num>1).

Round-4 VERDICT weak #4: the workers prove parity but not throughput.
This measures the dispatch-bound regime they exist for: a small dense
step (fc tower, batch 64) where per-step latency is dominated by
host-side dispatch + fetch (through the axon tunnel, ~100 ms
round-trip), not device compute. N workers overlap those blocking
round-trips against one shared compiled step — the hogwild_worker.cc
throughput story with XLA replacing the per-thread op execution.

    python tools/hogwild_bench.py      # prints one JSON line
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers  # noqa: E402
from paddle_tpu.framework import (Executor, Program, Scope,  # noqa: E402
                                  program_guard, unique_name)
from paddle_tpu.optimizer import SGDOptimizer  # noqa: E402


class _FeedStream:
    """Minimal Dataset facade: batch_iterator() over prebuilt feeds."""

    def __init__(self, feeds):
        self._feeds = feeds

    def batch_iterator(self, drop_last=False):
        return iter(self._feeds)


def build(seed=3):
    main, startup = Program(), Program()
    main.random_seed = startup.random_seed = seed
    with program_guard(main, startup), unique_name.guard():
        x = layers.data("x", [32])
        y = layers.data("y", [1])
        h = layers.fc(x, 64, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        SGDOptimizer(0.01).minimize(loss)
    return main, startup, loss


def run_mode(thread_num, n_batches=60, batch=64):
    main, startup, loss = build()
    scope = Scope()
    # hogwild needs a non-donating executor (shared scope buffers)
    exe = Executor(donate_state=False)
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.randn(batch, 32).astype(np.float32),
              "y": rng.randn(batch, 1).astype(np.float32)}
             for _ in range(n_batches)]
    from paddle_tpu.trainer_desc import MultiTrainer
    desc = MultiTrainer()
    desc.set_thread(thread_num)
    # warmup/compile outside the timed window
    exe.train_from_dataset(main, _FeedStream(feeds[:2]), scope=scope,
                           fetch_list=[loss.name], trainer_desc=desc)
    t0 = time.perf_counter()
    exe.train_from_dataset(main, _FeedStream(feeds), scope=scope,
                           fetch_list=[loss.name], trainer_desc=desc)
    dt = time.perf_counter() - t0
    return n_batches * batch / dt, dt / n_batches


def main():
    import jax
    results = {}
    for n in (1, 2, 4):
        ex_s, step_s = run_mode(n)
        results[n] = (round(ex_s, 1), round(step_s * 1e3, 2))
    base = results[1][0]
    best_n = max(results, key=lambda n: results[n][0])
    print(json.dumps({
        "metric": "hogwild_speedup_best",
        "value": round(results[best_n][0] / base, 3), "unit": "x",
        "best_thread_num": best_n,
        "examples_per_sec": {str(n): results[n][0] for n in results},
        "step_ms": {str(n): results[n][1] for n in results},
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
    }))


if __name__ == "__main__":
    main()

"""Graph-only builders for the eight book programs.

Each builder constructs the same Program IR as the corresponding
end-to-end test in tests/test_book.py — layers, backward pass, and
optimizer update ops included — but stops before the training loop, so
building all eight takes well under a second and never touches the
executor.  They exist so tools/lint_program.py (and the CI lint step)
can run the static verifier in paddle_tpu/framework/analysis.py over
realistic whole-model IR, including nested DynamicRNN sub-blocks,
without paying for training.  tests/test_program_verifier.py asserts
every builder verifies clean; keep a builder's geometry in sync with
its test_book.py twin when either changes.

Each builder returns (main_program, startup_program, fetch_names);
fetch_names are the variables the training loop would fetch, which the
verifier uses as dead-code roots.
"""

from collections import OrderedDict

import paddle_tpu as paddle
from paddle_tpu.framework import Program, program_guard, unique_name

fluid = paddle.fluid

BOOK_BUILDERS = OrderedDict()


def _register(fn):
    BOOK_BUILDERS[fn.__name__] = fn
    return fn


@_register
def fit_a_line():
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        x = fluid.layers.data(name='x', shape=[13], dtype='float32')
        y_predict = fluid.layers.fc(input=x, size=1, act=None)
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        cost = fluid.layers.square_error_cost(input=y_predict, label=y)
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.001).minimize(avg_cost)
    return main, startup, [avg_cost.name]


@_register
def recognize_digits_conv():
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        img = fluid.layers.data(name='img', shape=[1, 28, 28],
                                dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        conv_pool_1 = fluid.nets.simple_img_conv_pool(
            input=img, filter_size=5, num_filters=20, pool_size=2,
            pool_stride=2, act="relu")
        conv_pool_1 = fluid.layers.batch_norm(conv_pool_1)
        conv_pool_2 = fluid.nets.simple_img_conv_pool(
            input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
            pool_stride=2, act="relu")
        prediction = fluid.layers.fc(input=conv_pool_2, size=10,
                                     act='softmax')
        loss = fluid.layers.cross_entropy(input=prediction, label=label)
        avg_loss = fluid.layers.mean(loss)
        acc = fluid.layers.accuracy(input=prediction, label=label)
        fluid.optimizer.Adam(learning_rate=0.001).minimize(avg_loss)
    return main, startup, [avg_loss.name, acc.name]


@_register
def word2vec():
    EMBED_SIZE, HIDDEN_SIZE = 32, 256
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        word_dict = paddle.dataset.imikolov.build_dict()
        dict_size = len(word_dict)
        words = [fluid.layers.data(name=n, shape=[1], dtype='int64')
                 for n in ('firstw', 'secondw', 'thirdw', 'forthw',
                           'nextw')]

        def emb(w):
            return fluid.layers.embedding(
                input=w, size=[dict_size, EMBED_SIZE], dtype='float32',
                is_sparse=True, param_attr='shared_w')

        concat_embed = fluid.layers.concat(
            input=[emb(w) for w in words[:4]], axis=1)
        hidden1 = fluid.layers.fc(input=concat_embed, size=HIDDEN_SIZE,
                                  act='sigmoid')
        predict_word = fluid.layers.fc(input=hidden1, size=dict_size,
                                       act='softmax')
        cost = fluid.layers.cross_entropy(input=predict_word,
                                          label=words[4])
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
    return main, startup, [avg_cost.name]


@_register
def image_classification():
    def conv_bn_layer(input, ch_out, filter_size, stride, padding,
                      act='relu', bias_attr=False):
        tmp = fluid.layers.conv2d(input=input, filter_size=filter_size,
                                  num_filters=ch_out, stride=stride,
                                  padding=padding, act=None,
                                  bias_attr=bias_attr)
        return fluid.layers.batch_norm(input=tmp, act=act)

    def shortcut(input, ch_in, ch_out, stride):
        if ch_in != ch_out:
            return conv_bn_layer(input, ch_out, 1, stride, 0, None)
        return input

    def basicblock(input, ch_in, ch_out, stride):
        tmp = conv_bn_layer(input, ch_out, 3, stride, 1)
        tmp = conv_bn_layer(tmp, ch_out, 3, 1, 1, act=None,
                            bias_attr=True)
        short = shortcut(input, ch_in, ch_out, stride)
        return fluid.layers.elementwise_add(x=tmp, y=short, act='relu')

    def layer_warp(block_func, input, ch_in, ch_out, count, stride):
        tmp = block_func(input, ch_in, ch_out, stride)
        for _ in range(1, count):
            tmp = block_func(tmp, ch_out, ch_out, 1)
        return tmp

    depth = 8
    n = (depth - 2) // 6
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        images = fluid.layers.data(name='pixel', shape=[3, 32, 32],
                                   dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        conv1 = conv_bn_layer(input=images, ch_out=16, filter_size=3,
                              stride=1, padding=1)
        res1 = layer_warp(basicblock, conv1, 16, 16, n, 1)
        res2 = layer_warp(basicblock, res1, 16, 32, n, 2)
        res3 = layer_warp(basicblock, res2, 32, 64, n, 2)
        pool = fluid.layers.pool2d(input=res3, pool_size=8,
                                   pool_type='avg', pool_stride=1)
        predict = fluid.layers.fc(input=pool, size=10, act='softmax')
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(cost)
        acc = fluid.layers.accuracy(input=predict, label=label)
        fluid.optimizer.Adam(learning_rate=0.001).minimize(avg_cost)
    return main, startup, [avg_cost.name, acc.name]


@_register
def label_semantic_roles():
    word_dict, verb_dict, label_dict = paddle.dataset.conll05.get_dict()
    word_dict_len, label_dict_len = len(word_dict), len(label_dict)
    pred_dict_len = len(verb_dict)
    mark_dict_len, word_dim, mark_dim = 2, 16, 5
    hidden_dim, depth, maxlen = 64, 4, 12

    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        names = ['word_data', 'ctx_n2_data', 'ctx_n1_data', 'ctx_0_data',
                 'ctx_p1_data', 'ctx_p2_data', 'verb_data', 'mark_data']
        feeds = [fluid.layers.data(name=n, shape=[maxlen], dtype='int64')
                 for n in names]
        target = fluid.layers.data(name='target', shape=[maxlen],
                                   dtype='int64')
        seq_len = fluid.layers.data(name='seq_len', shape=[],
                                    dtype='int64')
        (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, predicate,
         mark) = feeds

        predicate_embedding = fluid.layers.embedding(
            input=predicate, size=[pred_dict_len, word_dim],
            dtype='float32', param_attr='vemb')
        mark_embedding = fluid.layers.embedding(
            input=mark, size=[mark_dict_len, mark_dim], dtype='float32')
        emb_layers = [
            fluid.layers.embedding(
                size=[word_dict_len, word_dim], input=x,
                param_attr=fluid.ParamAttr(name='emb'))
            for x in (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2)]
        emb_layers += [predicate_embedding, mark_embedding]

        hidden_0 = fluid.layers.sums(input=[
            fluid.layers.fc(input=emb, size=hidden_dim,
                            num_flatten_dims=2)
            for emb in emb_layers])
        lstm_0, _ = fluid.layers.dynamic_lstm(
            input=hidden_0, size=hidden_dim, sequence_length=seq_len,
            candidate_activation='relu', gate_activation='sigmoid',
            cell_activation='sigmoid')

        input_tmp = [hidden_0, lstm_0]
        for i in range(1, depth):
            mix_hidden = fluid.layers.sums(input=[
                fluid.layers.fc(input=input_tmp[0], size=hidden_dim,
                                num_flatten_dims=2),
                fluid.layers.fc(input=input_tmp[1], size=hidden_dim,
                                num_flatten_dims=2)])
            lstm, _ = fluid.layers.dynamic_lstm(
                input=mix_hidden, size=hidden_dim,
                sequence_length=seq_len,
                candidate_activation='relu', gate_activation='sigmoid',
                cell_activation='sigmoid', is_reverse=((i % 2) == 1))
            input_tmp = [mix_hidden, lstm]

        feature_out = fluid.layers.sums(input=[
            fluid.layers.fc(input=input_tmp[0], size=label_dict_len,
                            num_flatten_dims=2, act='tanh'),
            fluid.layers.fc(input=input_tmp[1], size=label_dict_len,
                            num_flatten_dims=2, act='tanh')])

        transition = fluid.layers.create_parameter(
            shape=[label_dict_len + 2, label_dict_len], dtype='float32',
            name='crfw')
        crf_cost = fluid.layers.linear_chain_crf(
            input=feature_out, label=target, param_attr=transition,
            length=seq_len)
        avg_cost = fluid.layers.mean(crf_cost)
        crf_decode = fluid.layers.crf_decoding(
            input=feature_out, param_attr=transition, length=seq_len)

        fluid.optimizer.SGD(
            learning_rate=fluid.layers.exponential_decay(
                learning_rate=0.01, decay_steps=100000,
                decay_rate=0.5, staircase=True)).minimize(avg_cost)
    return main, startup, [avg_cost.name, crf_decode.name]


@_register
def recommender_system():
    layers, nets = fluid.layers, fluid.nets
    IS_SPARSE = True
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        seq4_len = layers.data(name='seq4_len', shape=[], dtype='int64')

        USR_DICT_SIZE = paddle.dataset.movielens.max_user_id() + 1
        uid = layers.data(name='user_id', shape=[1], dtype='int64')
        usr_emb = layers.embedding(
            input=uid, dtype='float32', size=[USR_DICT_SIZE, 32],
            param_attr='user_table', is_sparse=IS_SPARSE)
        usr_fc = layers.fc(input=usr_emb, size=32)

        usr_gender_id = layers.data(name='gender_id', shape=[1],
                                    dtype='int64')
        usr_gender_emb = layers.embedding(
            input=usr_gender_id, size=[2, 16],
            param_attr='gender_table', is_sparse=IS_SPARSE)
        usr_gender_fc = layers.fc(input=usr_gender_emb, size=16)

        USR_AGE_DICT_SIZE = len(paddle.dataset.movielens.age_table)
        usr_age_id = layers.data(name='age_id', shape=[1], dtype="int64")
        usr_age_emb = layers.embedding(
            input=usr_age_id, size=[USR_AGE_DICT_SIZE, 16],
            is_sparse=IS_SPARSE, param_attr='age_table')
        usr_age_fc = layers.fc(input=usr_age_emb, size=16)

        USR_JOB_DICT_SIZE = paddle.dataset.movielens.max_job_id() + 1
        usr_job_id = layers.data(name='job_id', shape=[1], dtype="int64")
        usr_job_emb = layers.embedding(
            input=usr_job_id, size=[USR_JOB_DICT_SIZE, 16],
            param_attr='job_table', is_sparse=IS_SPARSE)
        usr_job_fc = layers.fc(input=usr_job_emb, size=16)

        usr = layers.fc(
            input=layers.concat(
                input=[usr_fc, usr_gender_fc, usr_age_fc, usr_job_fc],
                axis=-1),
            size=200, act="tanh")
        usr = layers.reshape(usr, [-1, 200])

        MOV_DICT_SIZE = paddle.dataset.movielens.max_movie_id() + 1
        mov_id = layers.data(name='movie_id', shape=[1], dtype='int64')
        mov_emb = layers.embedding(
            input=mov_id, dtype='float32', size=[MOV_DICT_SIZE, 32],
            param_attr='movie_table', is_sparse=IS_SPARSE)
        mov_fc = layers.fc(input=mov_emb, size=32)

        CATEGORY_DICT_SIZE = len(
            paddle.dataset.movielens.movie_categories())
        category_id = layers.data(name='category_id', shape=[4],
                                  dtype='int64')
        mov_categories_emb = layers.embedding(
            input=category_id, size=[CATEGORY_DICT_SIZE, 32],
            is_sparse=IS_SPARSE)
        mov_categories_hidden = layers.sequence_pool(
            input=mov_categories_emb, pool_type="sum",
            sequence_length=seq4_len)

        MOV_TITLE_DICT_SIZE = len(
            paddle.dataset.movielens.get_movie_title_dict())
        mov_title_id = layers.data(name='movie_title', shape=[4],
                                   dtype='int64')
        mov_title_emb = layers.embedding(
            input=mov_title_id, size=[MOV_TITLE_DICT_SIZE, 32],
            is_sparse=IS_SPARSE)
        mov_title_conv = nets.sequence_conv_pool(
            input=mov_title_emb, num_filters=32, filter_size=3,
            act="tanh", pool_type="sum", sequence_length=seq4_len)

        mov = layers.fc(
            input=layers.concat(
                input=[mov_fc, mov_categories_hidden, mov_title_conv],
                axis=-1),
            size=200, act="tanh")

        inference = layers.cos_sim(X=usr, Y=mov)
        scale_infer = layers.scale(x=inference, scale=5.0)
        label = layers.data(name='score', shape=[1], dtype='float32')
        square_cost = layers.square_error_cost(input=scale_infer,
                                               label=label)
        avg_cost = layers.mean(square_cost)
        fluid.optimizer.SGD(learning_rate=0.2).minimize(avg_cost)
    return main, startup, [avg_cost.name]


@_register
def rnn_encoder_decoder():
    dict_size, hidden_dim, embedding_dim = 200, 32, 16
    encoder_size = decoder_size = hidden_dim
    SRC_LEN, TRG_LEN = 8, 6

    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        def bi_lstm_encoder(input_seq, hidden_size, seq_len):
            input_forward_proj = fluid.layers.fc(
                input=input_seq, size=hidden_size * 4,
                num_flatten_dims=2, bias_attr=True)
            forward, _ = fluid.layers.dynamic_lstm(
                input=input_forward_proj, size=hidden_size * 4,
                sequence_length=seq_len, use_peepholes=False)
            input_backward_proj = fluid.layers.fc(
                input=input_seq, size=hidden_size * 4,
                num_flatten_dims=2, bias_attr=True)
            backward, _ = fluid.layers.dynamic_lstm(
                input=input_backward_proj, size=hidden_size * 4,
                is_reverse=True, sequence_length=seq_len,
                use_peepholes=False)
            forward_last = fluid.layers.sequence_last_step(
                input=forward, sequence_length=seq_len)
            backward_first = fluid.layers.sequence_first_step(
                input=backward, sequence_length=seq_len)
            return forward_last, backward_first

        def lstm_step(x_t, hidden_t_prev, cell_t_prev, size):
            def linear(inputs):
                return fluid.layers.fc(input=inputs, size=size,
                                       bias_attr=True)

            forget_gate = fluid.layers.sigmoid(
                linear([hidden_t_prev, x_t]))
            input_gate = fluid.layers.sigmoid(
                linear([hidden_t_prev, x_t]))
            output_gate = fluid.layers.sigmoid(
                linear([hidden_t_prev, x_t]))
            cell_tilde = fluid.layers.tanh(linear([hidden_t_prev, x_t]))
            cell_t = fluid.layers.sums(input=[
                fluid.layers.elementwise_mul(x=forget_gate,
                                             y=cell_t_prev),
                fluid.layers.elementwise_mul(x=input_gate,
                                             y=cell_tilde)])
            hidden_t = fluid.layers.elementwise_mul(
                x=output_gate, y=fluid.layers.tanh(cell_t))
            return hidden_t, cell_t

        src_word_idx = fluid.layers.data(name='source_sequence',
                                         shape=[SRC_LEN], dtype='int64')
        src_len = fluid.layers.data(name='src_len', shape=[],
                                    dtype='int64')
        src_embedding = fluid.layers.embedding(
            input=src_word_idx, size=[dict_size, embedding_dim],
            dtype='float32')
        src_forward_last, src_backward_first = bi_lstm_encoder(
            src_embedding, encoder_size, src_len)
        encoded_vector = fluid.layers.concat(
            input=[src_forward_last, src_backward_first], axis=1)
        decoder_boot = fluid.layers.fc(input=src_backward_first,
                                       size=decoder_size,
                                       bias_attr=False, act='tanh')
        trg_word_idx = fluid.layers.data(name='target_sequence',
                                         shape=[TRG_LEN], dtype='int64')
        trg_embedding = fluid.layers.embedding(
            input=trg_word_idx, size=[dict_size, embedding_dim],
            dtype='float32')

        rnn = fluid.layers.DynamicRNN()
        cell_init = fluid.layers.fill_constant_batch_size_like(
            input=decoder_boot, value=0.0, shape=[-1, decoder_size],
            dtype='float32')
        cell_init.stop_gradient = False
        with rnn.block():
            current_word = rnn.step_input(trg_embedding)
            context_in = rnn.static_input(encoded_vector)
            hidden_mem = rnn.memory(init=decoder_boot, need_reorder=True)
            cell_mem = rnn.memory(init=cell_init)
            decoder_inputs = fluid.layers.concat(
                input=[context_in, current_word], axis=1)
            h, c = lstm_step(decoder_inputs, hidden_mem, cell_mem,
                             decoder_size)
            rnn.update_memory(hidden_mem, h)
            rnn.update_memory(cell_mem, c)
            out = fluid.layers.fc(input=h, size=dict_size,
                                  bias_attr=True, act='softmax')
            rnn.output(out)
        prediction = rnn()

        label = fluid.layers.data(name='label_sequence',
                                  shape=[TRG_LEN], dtype='int64')
        flat_pred = fluid.layers.reshape(prediction, [-1, dict_size])
        flat_label = fluid.layers.reshape(label, [-1, 1])
        cost = fluid.layers.cross_entropy(input=flat_pred,
                                          label=flat_label)
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.Adagrad(learning_rate=0.05).minimize(avg_cost)
    return main, startup, [avg_cost.name]


@_register
def machine_translation_train():
    pd = fluid.layers
    dict_size, hidden_dim, word_dim = 200, 32, 16
    decoder_size = hidden_dim
    SRC_LEN, TRG_LEN = 8, 6

    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        src_word_id = pd.data(name="src_word_id", shape=[SRC_LEN],
                              dtype='int64')
        src_len = pd.data(name="src_len", shape=[], dtype='int64')
        src_embedding = pd.embedding(
            input=src_word_id, size=[dict_size, word_dim],
            dtype='float32', is_sparse=True,
            param_attr=fluid.ParamAttr(name='vemb'))
        fc1 = pd.fc(input=src_embedding, size=hidden_dim * 4,
                    num_flatten_dims=2, act='tanh')
        lstm_hidden0, _ = pd.dynamic_lstm(
            input=fc1, size=hidden_dim * 4, sequence_length=src_len)
        context = pd.sequence_last_step(input=lstm_hidden0,
                                        sequence_length=src_len)

        trg_language_word = pd.data(name="target_language_word",
                                    shape=[TRG_LEN], dtype='int64')
        trg_embedding = pd.embedding(
            input=trg_language_word, size=[dict_size, word_dim],
            dtype='float32', is_sparse=True,
            param_attr=fluid.ParamAttr(name='vemb'))
        rnn = pd.DynamicRNN()
        with rnn.block():
            current_word = rnn.step_input(trg_embedding)
            pre_state = rnn.memory(init=context)
            current_state = pd.fc(
                input=[current_word, pre_state], size=decoder_size,
                act='tanh')
            current_score = pd.fc(input=current_state, size=dict_size,
                                  act='softmax')
            rnn.update_memory(pre_state, current_state)
            rnn.output(current_score)
        rnn_out = rnn()

        label = pd.data(name="target_language_next_word",
                        shape=[TRG_LEN], dtype='int64')
        cost = pd.cross_entropy(
            input=pd.reshape(rnn_out, [-1, dict_size]),
            label=pd.reshape(label, [-1, 1]))
        avg_cost = pd.mean(cost)
        fluid.optimizer.Adagrad(
            learning_rate=0.05,
            regularization=fluid.regularizer.L2DecayRegularizer(
                regularization_coeff=1e-4)).minimize(avg_cost)
    return main, startup, [avg_cost.name]


def build_all():
    """Yield (name, main, startup, fetch_names) for all eight programs."""
    for name, builder in BOOK_BUILDERS.items():
        main, startup, fetches = builder()
        yield name, main, startup, fetches

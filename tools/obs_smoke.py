#!/usr/bin/env python
"""CI observability gate: tiny train + serving smoke under the run log.

Asserts, end to end through the observability plane:
  - a guarded training run (with one injected-NaN batch) emits
    train_step / guardian_skip / fault_injected run-log events;
  - a serving run emits serving_admit / serving_finish events;
  - the compile tracker reports decode_step_paged compile-count == 1
    and the batched same-bucket paged prefill dispatched exactly once
    (the PR 3/4 invariants, regression-locked via the new plane);
  - a repeated prompt scores a prefix-cache hit (STAT_serving_prefix_hits)
    without adding a single compile;
  - rerunning the same workload with FLAGS_serving_attn_impl=pallas +
    FLAGS_serving_kv_dtype=int8 (fused paged kernel in interpret mode,
    quantized KV pool) stays token-identical, retraces each site exactly
    once (flags-version keying), and the merged two-phase recompile
    prediction still equals the live tracker;
  - the same workload through two ReplicaRouter replicas (shared model
    => shared step cache: two replicas compile like one engine) and
    through a 1x1 ("data", "model") serving mesh (new mesh cache key:
    exactly one more compile per site) stays token-identical, with the
    merged four-phase prediction still equal to the tracker;
  - a seeded bursty loadgen run through an engine with SLO-aware
    admission (constructor-arg SLO/pins/priorities, never set_flags)
    completes with goodput > 0, zero leaked KV blocks and ZERO new
    compiles — and the recompile predictor agrees the admission
    parameters are no-ops;
  - the same workload through a 1 prefill x 2 decode DisaggRouter
    fleet stays token-identical with ZERO new compiles (role-split
    engines share the symmetric engines' step cache), scores a
    prefix-affinity routing hit on the repeated prompt, leaks no KV
    blocks, and matches the predictor's ``disagg`` no-op claim;
  - a kill -> re-home -> restart episode on a 2-replica router: the
    killed replica's work finishes token-identically on the survivor,
    health states and re-home counters publish to /metrics and the
    run log, the tracker does not move, and the predictor agrees
    replica_kills/restarts/rehomed are no-ops;
  - a live weight hot-swap (``swap_weights``) into the still-warm
    loadgen engine adds zero compiles, decodes the new weights'
    greedy tokens, and matches the predictor's ``weight_swaps``
    no-op claim;
  - mixed greedy / sampled / JSON-constrained / two-tenant-LoRA
    traffic on one engine (pool geometry via set_flags = one fresh
    phase like pallas+int8): the json_mode row decodes to valid JSON,
    tenants diverge from base, a mid-flight ``load_adapter`` and the
    whole second wave add ZERO compiles, the per-phase compile delta
    equals the predictor's claim (``sampling`` recipes are validated
    no-ops, ``lora`` geometry is one retrace), and neither KV blocks
    nor adapter pages leak;
  - per-request tracing (FLAGS_serving_trace, default-on) on a traced
    burst through a fresh engine: every finished request's blame
    decomposition sums exactly to its measured E2E (the accounting
    identity in paddle_tpu/observability/tracing.py), the chrome-trace
    export is a Perfetto-loadable document with one flow per request,
    GET /v1/requests/<id> serves the span timeline (and 404s unknown
    ids), and the predictor agrees ``tracing`` never compiles —
    per-phase predicted counts equal the live tracker;
  - the static serving lint (``analysis.lint_serving``) reports zero
    findings on the shipped fleet, and replaying the loadgen workload
    under ``FLAGS_sanitize_locks=1`` keeps goodput within 5% of the
    plain run, records zero lock-order cycles / guarded-state
    violations over nonzero instrumented acquires, and matches the
    predictor's ``sanitize`` no-op claim (predicted == observed);
  - a cancel/hedge episode on a hedging 2-replica router (one hedge
    race fired and won against a deterministic straggler; cancels at
    the queued and mid-decode stages plus the race's loser) leaks
    nothing, logs serving_cancel / serving_hedge events, mints the
    canceled/hedge/retry-budget metrics, and matches the predictor's
    ``cancel``/``hedge`` no-op claims (predicted == observed);
  - a host-KV-tier session episode (FLAGS_serving_host_tier, explicit
    ``kv_tier=``): a two-turn session is demoted to host RAM by the
    idle sweep, resumed token-identically (the resumed turn equals
    replaying the stored conversation as a plain prompt), drains both
    tiers leak-free, logs serving_kv_demote / serving_kv_promote /
    serving_session_resume events, mints the migration/session
    metrics, and matches the predictor's ``host_tier``/``sessions``
    validated-no-op claim (predicted == observed);
  - a device-resident decode-megastep episode
    (FLAGS_serving_megastep=4 + FLAGS_serving_dispatch_ahead): N
    decode iterations per compiled dispatch stay token-identical to a
    megastep=1 engine at the same flags version, the decode plane
    traces exactly its TWO predicted surfaces (the megastep entry and
    the single-token fallback a caps-exceeding stop list forces), no
    KV blocks leak, and predict_serving_compiles(megastep=4) equals
    the live tracker;
  - a device-cost-observatory episode (FLAGS_serving_devprof,
    sample=1.0): every compile's XLA cost_analysis is captured into
    the cost table / ``xla_cost`` gauges by an out-of-band lowering
    that adds ZERO compiles (``predict_serving_compiles(devprof=
    True)`` is a validated no-op, predicted == observed), every
    sampled dispatch feeds the roofline/MFU gauges, each traced
    request's blame splits ``decode`` into ``decode_device`` +
    ``decode_host`` with the reconciliation identity intact, and
    ``/v1/stats`` serves the devprof section;
  - GET /metrics on ServingHTTPServer parses as Prometheus text and
    carries serving, fault, compile, KV block-pool, attention-impl,
    int8-quantization, SLO-admission, tracing and device-cost
    (xla_cost / MFU / HBM-utilization / host-overhead) metrics;
  - tools/trace_summary.py consumes the emitted JSONL run log.

Run from the repo root:  JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="obs_smoke_")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import layers, monitor, observability
    from paddle_tpu.framework import (Executor, Program, Scope,
                                      program_guard, unique_name)
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import runlog
    from paddle_tpu.optimizer import SGDOptimizer
    from paddle_tpu.resilience import TrainGuardian, fault_scope
    from paddle_tpu.serving import ServingEngine, ServingHTTPServer

    pt.set_flags({"runlog_dir": tmp})

    # -- tiny train under the guardian, with one injected NaN batch ----
    main_p, startup = Program(), Program()
    main_p.random_seed = startup.random_seed = 5
    with program_guard(main_p, startup), unique_name.guard():
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        SGDOptimizer(0.1).minimize(loss)
    scope, exe = Scope(), Executor()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    pt.set_flags({"check_nan_inf": True})
    try:
        with fault_scope("exec.step:nan@3"):
            guardian = TrainGuardian(exe, main_p, scope)
            for _ in range(5):
                xb = rng.rand(8, 4).astype(np.float32)
                yb = (xb.sum(1, keepdims=True) +
                      rng.rand(8, 1).astype(np.float32) * 0.1)
                guardian.step(feed={"x": xb, "y": yb},
                              fetch_list=[loss.name])
    finally:
        pt.set_flags({"check_nan_inf": False})
    assert guardian.skipped == 1, guardian.skipped
    print(f"   train: {guardian.steps_done} steps, "
          f"{guardian.skipped} NaN skip")

    # -- serving smoke: 3 same-bucket prompts through 3 slots ----------
    pt.seed(7)
    cfg = GPTConfig(vocab_size=97, max_position_embeddings=64,
                    hidden_size=32, num_layers=2, num_heads=4,
                    ffn_hidden_size=64)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = ServingEngine(model, max_slots=3, max_len=32,
                        buckets=[8, 16], max_queue=16, block_size=4)
    prompts = [rng.randint(1, 97, size=n).tolist() for n in (3, 5, 7)]
    reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
    eng.step()
    prefill_calls = monitor.stat_get("STAT_serving_prefill_calls")
    assert prefill_calls == 1, (
        f"expected ONE batched prefill dispatch, saw {prefill_calls}")
    eng.run_until_idle()
    assert all(r.state == "done" for r in reqs)

    comp = observability.compiles()
    assert comp["decode_step_paged"]["count"] == 1, \
        comp.get("decode_step_paged")
    assert comp["serving_prefill_paged{bucket=8}"]["count"] == 1, comp
    assert comp["decode_step_paged"]["last_signature"], \
        "no compile signature"
    print(f"   compile tracker: decode_step_paged=1, "
          f"prefill_paged{{bucket=8}}=1 ({len(comp)} tracked sites)")

    # -- prefix-cache reuse: repeat a prompt, expect a hit -------------
    rep = eng.submit(prompts[2], max_new_tokens=4)
    eng.run_until_idle()
    assert rep.state == "done" and rep.output_ids == reqs[2].output_ids
    hits = monitor.stat_get("STAT_serving_prefix_hits")
    assert hits >= 1, f"repeated prompt scored no prefix hit ({hits})"
    comp2 = observability.compiles()
    assert comp2["decode_step_paged"]["count"] == 1, \
        "prefix reuse must not retrace decode"
    print(f"   prefix cache: repeat hit ({hits} hit admissions), "
          f"0 new compiles")

    # -- static recompile prediction == observed compile tracker ------
    # The same workload, predicted before-the-fact by the abstract
    # model in paddle_tpu/analysis/recompile.py: round 1 admits the
    # three prompts together, round 2 re-submits prompts[2] (whose
    # full-block prefix is published by then). Predicted tracked_jit
    # counts must equal the observed ones, both directions.
    from paddle_tpu.analysis import (merge_compile_counts,
                                     predict_serving_compiles)
    workload = [[(p, 4) for p in prompts], [(prompts[2], 4)]]
    predicted = predict_serving_compiles(
        workload, buckets=[8, 16], max_len=32, block_size=4)
    observed = {site: c["count"] for site, c in comp2.items()
                if site.startswith(("serving_", "decode_", "verify_"))}
    assert predicted == observed, (
        f"recompile prediction drifted from the live tracker:\n"
        f"  predicted {predicted}\n  observed  {observed}")
    print(f"   recompile predictor: {predicted} == observed")

    # -- pallas + int8 phase: same workload, fused kernel + quantized
    # KV pool. set_flags bumps the flags version, so each site retraces
    # exactly once; outputs must stay token-identical and the merged
    # two-phase prediction must equal the tracker.
    pt.set_flags({"serving_attn_impl": "pallas",
                  "serving_kv_dtype": "int8"})
    try:
        eng2 = ServingEngine(model, max_slots=3, max_len=32,
                             buckets=[8, 16], max_queue=16, block_size=4)
        reqs2 = [eng2.submit(p, max_new_tokens=4) for p in prompts]
        eng2.run_until_idle()
        rep2 = eng2.submit(prompts[2], max_new_tokens=4)
        eng2.run_until_idle()
        for a, b in zip(reqs + [rep], reqs2 + [rep2]):
            assert a.output_ids == b.output_ids, (
                f"pallas+int8 diverged on request {b.id}: "
                f"{a.output_ids} vs {b.output_ids}")
        st2 = eng2.stats()
        assert st2["attn_impl"] == "pallas" and st2["kv_dtype"] == "int8"
        assert st2["kv_quant_max_abs_err"] > 0.0, st2
        writes = monitor.stat_get("STAT_serving_kv_quant_writes")
        assert writes >= 1, writes
        predicted2 = predict_serving_compiles(
            workload, buckets=[8, 16], max_len=32, block_size=4,
            attn_impl="pallas", kv_dtype="int8")
        merged = merge_compile_counts(predicted, predicted2)
        comp3 = observability.compiles()
        observed3 = {site: c["count"] for site, c in comp3.items()
                     if site.startswith(("serving_", "decode_",
                                         "verify_"))}
        assert merged == observed3, (
            f"two-phase recompile prediction drifted:\n"
            f"  predicted {merged}\n  observed  {observed3}")
        print(f"   pallas+int8: token-identical, max_abs_err="
              f"{st2['kv_quant_max_abs_err']}, merged prediction == "
              f"observed")
    finally:
        pt.set_flags({"serving_attn_impl": "xla",
                      "serving_kv_dtype": "f32"})

    # -- mesh + replica phase: the same workload on (a) two data-
    # parallel replicas behind the ReplicaRouter and (b) a 1x1
    # ("data", "model") serving mesh. The finally above bumped the
    # flags version, so the router's engines retrace each site once
    # (one phase) — but BOTH replicas share the model and therefore
    # the unified step cache, so two replicas add the counts of ONE
    # engine (the n_replicas invariant). The mesh engine's steps live
    # under a new mesh cache key: one more compile per site (a fourth
    # phase). Outputs must stay token-identical throughout, and the
    # four-phase merged prediction must equal the live tracker.
    from paddle_tpu.distributed.sharding import serving_mesh
    from paddle_tpu.serving import ReplicaRouter
    router = ReplicaRouter(model, n_replicas=2, max_slots=3,
                           max_len=32, buckets=[8, 16], max_queue=16,
                           block_size=4)
    reqs3 = [router.submit(p, max_new_tokens=4) for p in prompts]
    router.run_until_idle()
    rep3 = router.submit(prompts[2], max_new_tokens=4)
    router.run_until_idle()
    for a, b in zip(reqs + [rep], reqs3 + [rep3]):
        assert a.output_ids == b.output_ids, (
            f"routed replica diverged on request {b.id}: "
            f"{a.output_ids} vs {b.output_ids}")
    st3 = router.stats()
    assert st3["replicas"] == 2 and len(st3["queue_depths"]) == 2, st3
    predicted3 = predict_serving_compiles(
        workload, buckets=[8, 16], max_len=32, block_size=4,
        n_replicas=2)

    mesh = serving_mesh(1, 1)
    eng4 = ServingEngine(model, max_slots=3, max_len=32,
                         buckets=[8, 16], max_queue=16, block_size=4,
                         mesh=mesh)
    reqs4 = [eng4.submit(p, max_new_tokens=4) for p in prompts]
    eng4.run_until_idle()
    rep4 = eng4.submit(prompts[2], max_new_tokens=4)
    eng4.run_until_idle()
    for a, b in zip(reqs + [rep], reqs4 + [rep4]):
        assert a.output_ids == b.output_ids, (
            f"mesh engine diverged on request {b.id}: "
            f"{a.output_ids} vs {b.output_ids}")
    st4 = eng4.stats()
    assert st4["mesh_shape"] == [1, 1], st4
    predicted4 = predict_serving_compiles(
        workload, buckets=[8, 16], max_len=32, block_size=4,
        mesh_shape=(1, 1))
    merged4 = merge_compile_counts(predicted, predicted2, predicted3,
                                   predicted4)
    comp4 = observability.compiles()
    observed4 = {site: c["count"] for site, c in comp4.items()
                 if site.startswith(("serving_", "decode_", "verify_"))}
    assert merged4 == observed4, (
        f"mesh-phase recompile prediction drifted:\n"
        f"  predicted {merged4}\n  observed  {observed4}")
    print(f"   mesh phase: 2 replicas + 1x1 mesh token-identical, "
          f"merged prediction == observed ({observed4})")

    # -- loadgen phase: SLO-aware admission adds ZERO compiles --------
    # A bursty open-loop workload on a virtual clock through an engine
    # with predictive admission (SLO + pinned costs + priority mix —
    # all constructor args, never set_flags, so the flags version and
    # the warm step cache survive). Prompt lengths stay inside the
    # already-compiled bucket: the tracker must not move at all, and
    # the predictor must agree that admission parameters are no-ops.
    from tools.loadgen import LoadGen, VirtualClock
    vc = VirtualClock()
    eng5 = ServingEngine(model, max_slots=3, max_len=32,
                         buckets=[8, 16], max_queue=16, block_size=4,
                         clock=vc.now, slo_ttft_ms=40.0,
                         slo_prefill_ms=4.0, slo_tpot_ms=1.0)
    lg = LoadGen(mode="bursty", rate=60.0, duration=1.0, seed=3,
                 vocab_size=97, prompt_tokens=(3, 7),
                 new_tokens=(2, 4),
                 priority_mix={0: 0.2, 1: 0.6, 2: 0.2})
    report = lg.run(eng5, clock=vc, step_cost_ms=4.0)
    assert report["offered"] > 0 and report["completed"] > 0, report
    assert report["exceptions"] == 0, report
    assert report["leaked_kv_blocks"] == 0, report
    assert report["slo_attainment"] is not None, report
    assert len(report["decisions"]) == report["offered"]
    comp5 = observability.compiles()
    observed5 = {site: c["count"] for site, c in comp5.items()
                 if site.startswith(("serving_", "decode_", "verify_"))}
    assert observed5 == observed4, (
        f"SLO-aware admission must add ZERO compiles:\n"
        f"  before {observed4}\n  after  {observed5}")
    lg_workload = [[(list(a.prompt), a.max_new_tokens)
                    for a in lg.schedule()]]
    plain_pred = predict_serving_compiles(
        lg_workload, buckets=[8, 16], max_len=32, block_size=4)
    slo_pred = predict_serving_compiles(
        lg_workload, buckets=[8, 16], max_len=32, block_size=4,
        slo_ttft_ms=40.0, priority_classes=[0, 1, 2],
        autoscale=(1, 2))
    assert slo_pred == plain_pred, (slo_pred, plain_pred)
    print(f"   loadgen: {report['completed']}/{report['offered']} done "
          f"(goodput {report['goodput_per_s']}/s, attainment "
          f"{report['slo_attainment']}, shed {report['shed_total']}), "
          f"0 new compiles")

    # -- disagg phase: P/D role split adds ZERO compiles --------------
    # (Before the hot-swap phase: swap_weights mutates the shared
    # model in place, so the old-weight reference outputs only hold
    # until then.) The same workload through a 1 prefill x 2 decode
    # DisaggRouter at the same geometry: both roles reuse the
    # symmetric engines' compiled steps (the step cache keys on
    # geometry, never role), the KV handoff is host-side block
    # surgery, and re-submitting prompts[2] scores a prefix-affinity
    # routing hit. Token-identical, tracker frozen, predictor agrees
    # disagg is a no-op, zero leaks.
    from paddle_tpu.serving import DisaggRouter
    fleet = DisaggRouter(model, n_prefill=1, n_decode=2, max_slots=3,
                         max_len=32, buckets=[8, 16], max_queue=16,
                         block_size=4)
    reqs7 = [fleet.submit(p, max_new_tokens=4) for p in prompts]
    fleet.run_until_idle()
    rep7 = fleet.submit(prompts[2], max_new_tokens=4)
    fleet.run_until_idle()
    for a, b in zip(reqs + [rep], reqs7 + [rep7]):
        assert a.output_ids == b.output_ids, (
            f"disagg fleet diverged on request {b.id}: "
            f"{a.output_ids} vs {b.output_ids}")
    st7 = fleet.stats()
    assert st7["prefill_workers"] == 1 and st7["decode_workers"] == 2
    assert st7["handoffs_adopted"] >= len(prompts), st7
    assert st7["affinity_hits"] >= 1, st7
    comp7 = observability.compiles()
    observed7 = {site: c["count"] for site, c in comp7.items()
                 if site.startswith(("serving_", "decode_", "verify_"))}
    assert observed7 == observed5, (
        f"disaggregated roles must add ZERO compiles:\n"
        f"  before {observed5}\n  after  {observed7}")
    disagg_pred = predict_serving_compiles(
        workload, buckets=[8, 16], max_len=32, block_size=4,
        disagg=(1, 2))
    assert disagg_pred == predicted, (disagg_pred, predicted)
    pools = {}
    for e in fleet.engines:
        pools[id(e.cache.pool)] = e.cache
    for cache in pools.values():
        cache.flush_prefix_cache()
        assert cache.allocator.leaked() == 1   # trash block only
    print(f"   disagg: 1x2 fleet token-identical, "
          f"{st7['handoffs_adopted']} handoffs "
          f"({st7['affinity_hits']} affinity hits), 0 new compiles, "
          f"0 leaked blocks")

    # -- fault-tolerance phase: kill -> re-home -> restart ------------
    # (Still before the hot-swap phase: the reference outputs hold
    # only while the shared model carries the old weights.) Load every
    # request onto replica 0, kill it: the queued work re-homes onto
    # the survivor and finishes token-identical. Then restart the
    # survivor in place. Kill + restart + re-home are host-side row
    # surgery over already-compiled buckets, so the tracker must not
    # move — and the predictor must agree the counts are no-ops.
    router9 = ReplicaRouter(model, n_replicas=2, max_slots=3,
                            max_len=32, buckets=[8, 16], max_queue=16,
                            block_size=4)
    reqs9 = [router9.engines[0].submit(p, max_new_tokens=4)
             for p in prompts]
    info9 = router9.kill_replica(0)
    assert info9["rehomed"] == len(prompts) and info9["shed"] == 0, \
        info9
    router9.run_until_idle()
    for a, b in zip(reqs, reqs9):
        assert a.output_ids == b.output_ids, (
            f"re-homed request {b.id} diverged: "
            f"{a.output_ids} vs {b.output_ids}")
    assert all(r.rehomed for r in reqs9)
    router9.restart_replica(0)
    router9.run_until_idle()
    st9 = router9.stats()
    assert st9["kills"] == 2 and st9["restarts"] == 1, st9
    assert st9["rehomed"] == len(prompts), st9
    assert st9["replicas"] == 1, st9   # restart replaces in place
    assert all(h in ("healthy", "recovering")
               for h in st9["health"]), st9
    ids9 = [r.id for r in router9.results()]
    assert len(ids9) == len(set(ids9)) == len(prompts)
    for e in router9.engines + router9._retiring:
        e.cache.flush_prefix_cache()
        assert e.cache.allocator.leaked() == 1   # trash block only
    comp9 = observability.compiles()
    observed9 = {site: c["count"] for site, c in comp9.items()
                 if site.startswith(("serving_", "decode_", "verify_"))}
    assert observed9 == observed7, (
        f"kill/re-home/restart must add ZERO compiles:\n"
        f"  before {observed7}\n  after  {observed9}")
    ft_pred = predict_serving_compiles(
        workload, buckets=[8, 16], max_len=32, block_size=4,
        n_replicas=2, replica_kills=2, restarts=1,
        rehomed=len(prompts))
    assert ft_pred == predicted3, (ft_pred, predicted3)
    print(f"   fault tolerance: kill -> {info9['rehomed']} re-homed "
          f"token-identical -> restart, health {st9['health']}, "
          f"0 new compiles (predicted == observed)")

    # -- hot-swap phase: live weight swap adds ZERO compiles ----------
    # Publish fresh weights into the still-warm loadgen engine: the
    # compiled steps take weights as explicit jit inputs, so the
    # tracker must not move, post-swap traffic must decode the NEW
    # model's greedy tokens, and the predictor must agree that
    # weight_swaps is a no-op.
    from paddle_tpu.models.generation import greedy_search
    pt.seed(23)
    swap_model = GPTForCausalLM(cfg)
    swap_model.eval()
    version = eng5.swap_weights(
        {n: p.value for n, p in swap_model.named_parameters()})
    assert version == 1 and eng5.weight_version == 1
    p_swap = rng.randint(1, 97, size=5).tolist()
    r_swap = eng5.submit(p_swap, max_new_tokens=4)
    eng5.run_until_idle()
    comp6 = observability.compiles()
    observed6 = {site: c["count"] for site, c in comp6.items()
                 if site.startswith(("serving_", "decode_", "verify_"))}
    assert observed6 == observed9, (
        f"live weight swap must add ZERO compiles:\n"
        f"  before {observed9}\n  after  {observed6}")
    ref_swap = greedy_search(swap_model, np.asarray([p_swap]),
                             max_new_tokens=4,
                             cache_len=32)[0].tolist()
    assert r_swap.output_ids == ref_swap, (
        "post-swap tokens != new-weight greedy")
    swap_pred = predict_serving_compiles(
        lg_workload, buckets=[8, 16], max_len=32, block_size=4,
        weight_swaps=1)
    assert swap_pred == plain_pred, (swap_pred, plain_pred)
    print(f"   hot swap: v{version} live, tokens match the new "
          f"weights, 0 new compiles (predicted == observed)")

    # -- decoding phase: sampling-as-data + multi-tenant paged LoRA ---
    # set_flags bumps the flags version (like the pallas phase) and the
    # adapter pool joins the step cache key, so the lora-shaped steps
    # retrace exactly once; after that first wave, mixed greedy /
    # sampled / json-constrained / multi-tenant traffic — including a
    # mid-flight load_adapter — must never move the tracker again, and
    # the predictor must agree sampling recipes are no-ops while the
    # lora geometry is one fresh phase.
    from paddle_tpu.serving import (JsonGrammar, json_token_strings,
                                    make_adapter)
    grammar = JsonGrammar(json_token_strings(97))
    # fresh baseline: the hot-swap phase's offline greedy reference
    # traced the dense decode_step after its own snapshot
    base8 = {site: c["count"]
             for site, c in observability.compiles().items()
             if site.startswith(("serving_", "decode_", "verify_"))}
    pt.set_flags({"serving_lora_rank": 2,
                  "serving_lora_max_adapters": 2})
    try:
        eng8 = ServingEngine(model, max_slots=3, max_len=32,
                             buckets=[8, 16], max_queue=16,
                             block_size=4, grammar=grammar)
        eng8.load_adapter("acme", make_adapter(cfg, 2, seed=1,
                                               scale=0.5))
        r_base = eng8.submit(prompts[2], max_new_tokens=4)
        r_samp = eng8.submit(prompts[1], max_new_tokens=4,
                             temperature=0.9, top_k=8, seed=11)
        r_acme = eng8.submit(prompts[2], max_new_tokens=4,
                             tenant="acme")
        eng8.run_until_idle()
        assert r_acme.output_ids != r_base.output_ids, (
            "tenant adapter did not change the decode")
        wave1 = {site: c["count"]
                 for site, c in observability.compiles().items()
                 if site.startswith(("serving_", "decode_", "verify_"))}
        eng8.load_adapter("zeta", make_adapter(cfg, 2, seed=2,
                                               scale=0.5))
        r_json = eng8.submit(prompts[0], max_new_tokens=8,
                             json_mode=True)
        r_zeta = eng8.submit(prompts[2], max_new_tokens=4,
                             tenant="zeta")
        eng8.run_until_idle()
        doc = grammar.decode(r_json.tokens)
        json.loads(doc)   # valid JSON by construction
        assert r_zeta.output_ids != r_acme.output_ids, (
            "tenants decoded identically")
        wave2 = {site: c["count"]
                 for site, c in observability.compiles().items()
                 if site.startswith(("serving_", "decode_", "verify_"))}
        assert wave2 == wave1, (
            f"mixed decode traffic + adapter load must add ZERO "
            f"compiles:\n  before {wave1}\n  after  {wave2}")
        delta8 = {site: n - base8.get(site, 0)
                  for site, n in wave2.items()
                  if n - base8.get(site, 0)}
        workload8 = [[(prompts[2], 4), (prompts[1], 4),
                      (prompts[2], 4)],
                     [(prompts[0], 8), (prompts[2], 4)]]
        predicted8 = predict_serving_compiles(
            workload8, buckets=[8, 16], max_len=32, block_size=4,
            sampling=[(0.9, 8, 1.0)], lora=(2, 2))
        assert delta8 == predicted8, (
            f"decoding-phase recompile prediction drifted:\n"
            f"  predicted {predicted8}\n  observed  {delta8}")
        st8 = eng8.stats()
        assert set(st8["lora"]["loaded"]) == {"acme", "zeta"}, \
            st8["lora"]
        assert st8["lora"]["leaked_pages"] == 0, st8["lora"]
        assert st8["json_grammar"] is True, st8
        assert set(st8["tenants"]) == {"base", "acme", "zeta"}, (
            st8["tenants"])
        assert eng8.lora_pool.leaked() == 0
        eng8.cache.flush_prefix_cache()
        assert eng8.cache.allocator.leaked() == 1   # trash block only
        print(f"   decoding: sampled/json/2-tenant mix on one engine, "
              f"json doc {doc!r} valid, 0 new compiles after the lora "
              f"phase ({delta8} == predicted)")
    finally:
        pt.set_flags({"serving_lora_rank": 0})

    # -- tracing phase: spans, blame identity, Perfetto, debug API ----
    # FLAGS_serving_trace defaults to 1.0, so every request above was
    # already traced — host-side (kind, t, track) marks on the engine
    # clock, never a jit input. Reset the ring and run a traced burst
    # on a fresh engine at the warm geometry: the decoding phase's
    # finally bumped the flags version, so each site retraces exactly
    # once (a fresh phase, like the pallas one) and the per-phase
    # delta must equal the predictor's claim WITH tracing=True — which
    # must itself equal the prediction without it (the no-op family).
    # Every finished request's blame components must sum exactly to
    # its measured E2E, the chrome export must be a Perfetto document
    # with flow events stitching each request across tracks, and the
    # HTTP debug endpoint must serve the timeline.
    from paddle_tpu.observability import tracing
    tracing.reset()
    baseT = {site: c["count"]
             for site, c in observability.compiles().items()
             if site.startswith(("serving_", "decode_", "verify_"))}
    engT = ServingEngine(model, max_slots=3, max_len=32,
                         buckets=[8, 16], max_queue=16, block_size=4)
    reqsT = [engT.submit(p, max_new_tokens=4) for p in prompts]
    engT.run_until_idle()
    assert all(r.state == "done" for r in reqsT)
    for r in reqsT:
        info = tracing.get(r.id)
        assert info is not None and info["outcome"] == "done", info
        gap = abs(sum(info["blame_ms"].values()) - info["e2e_ms"])
        assert gap < 1e-6, (
            f"blame identity broke on request {r.id}: components "
            f"{info['blame_ms']} vs e2e {info['e2e_ms']} (gap {gap})")
    docT = tracing.export_chrome_trace()
    spansT = [e for e in docT["traceEvents"] if e.get("ph") == "X"]
    flowsT = [e for e in docT["traceEvents"]
              if e.get("ph") in ("s", "t", "f")]
    assert spansT and len(flowsT) >= 1, (len(spansT), len(flowsT))
    assert {e["args"]["request"] for e in spansT} == \
        set(range(len(reqsT))), spansT
    afterT = {site: c["count"]
              for site, c in observability.compiles().items()
              if site.startswith(("serving_", "decode_", "verify_"))}
    deltaT = {site: n - baseT.get(site, 0) for site, n in afterT.items()
              if n - baseT.get(site, 0)}
    burstT = [[(p, 4) for p in prompts]]
    predT = predict_serving_compiles(
        burstT, buckets=[8, 16], max_len=32, block_size=4,
        tracing=True)
    assert predT == predict_serving_compiles(
        burstT, buckets=[8, 16], max_len=32, block_size=4), (
        "tracing must be a predictor no-op")
    assert deltaT == predT, (
        f"tracing-phase recompile prediction drifted:\n"
        f"  predicted {predT}\n  observed  {deltaT}")
    srvT = ServingHTTPServer(engT, port=0)
    srvT.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srvT.port}/v1/requests/"
                f"{reqsT[0].id}", timeout=10) as r:
            assert r.status == 200
            got = json.loads(r.read().decode())
        assert got["outcome"] == "done" and got["marks"], got
        assert got["blame_ms"] == tracing.get(reqsT[0].id)["blame_ms"]
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srvT.port}/v1/requests/999999",
                timeout=10)
            raise AssertionError("unknown request id must 404")
        except urllib.error.HTTPError as e404:
            assert e404.code == 404, e404.code
    finally:
        srvT.stop()
    print(f"   tracing: {len(reqsT)} traced requests, blame sums == "
          f"E2E, {len(spansT)} spans / {len(flowsT)} flow events, "
          f"/v1/requests/<id> 200+404, {deltaT} == predicted")

    # -- sanitize phase: the concurrency sanitizer is free ------------
    # Replay the loadgen workload with FLAGS_sanitize_locks=1: every
    # engine/router/metrics lock becomes a SanitizedLock recording
    # order edges and guarded-state writes. The flag gates pure host
    # bookkeeping, so (a) the predictor says sanitize=True compiles
    # NOTHING new (validated no-op, like tracing) and the fresh-phase
    # delta equals that prediction, (b) goodput stays within 5% of the
    # plain loadgen run on the same virtual-clock schedule, and (c)
    # the report comes back with zero cycles, zero violations, and
    # nonzero instrumented acquires. The static half must agree the
    # fleet is clean: lint_serving() returns zero findings.
    from paddle_tpu.analysis import concurrency as ccz
    from paddle_tpu.analysis import lint_serving as lint_serving_fn
    lint_res = lint_serving_fn()
    assert not lint_res.diagnostics, (
        f"lint_serving found issues in the shipped fleet: "
        f"{[str(d) for d in lint_res.diagnostics]}")
    ccz.reset()
    baseS = {site: c["count"]
             for site, c in observability.compiles().items()
             if site.startswith(("serving_", "decode_", "verify_"))}
    pt.set_flags({"sanitize_locks": True})
    try:
        vcS = VirtualClock()
        engS = ServingEngine(model, max_slots=3, max_len=32,
                             buckets=[8, 16], max_queue=16,
                             block_size=4, clock=vcS.now,
                             slo_ttft_ms=40.0, slo_prefill_ms=4.0,
                             slo_tpot_ms=1.0)
        lgS = LoadGen(mode="bursty", rate=60.0, duration=1.0, seed=3,
                      vocab_size=97, prompt_tokens=(3, 7),
                      new_tokens=(2, 4),
                      priority_mix={0: 0.2, 1: 0.6, 2: 0.2})
        reportS = lgS.run(engS, clock=vcS, step_cost_ms=4.0)
        sanS = ccz.report()
    finally:
        pt.set_flags({"sanitize_locks": False})
    assert reportS["exceptions"] == 0, reportS
    assert reportS["leaked_kv_blocks"] == 0, reportS
    assert reportS["completed"] > 0, reportS
    assert abs(reportS["goodput_per_s"] - report["goodput_per_s"]) \
        <= 0.05 * report["goodput_per_s"], (
        f"sanitized goodput {reportS['goodput_per_s']}/s strayed >5% "
        f"from plain {report['goodput_per_s']}/s")
    assert sanS["enabled"] and sanS["lock_acquires"] > 0, sanS
    # the fresh engine's queue + step locks (the registry lock predates
    # the flag flip, so it stays plain in-process)
    assert sanS["locks_tracked"] >= 2, sanS
    assert sanS["cycles"] == [], sanS["cycles"]
    assert sanS["violations"] == [], sanS["violations"]
    afterS = {site: c["count"]
              for site, c in observability.compiles().items()
              if site.startswith(("serving_", "decode_", "verify_"))}
    deltaS = {site: n - baseS.get(site, 0) for site, n in afterS.items()
              if n - baseS.get(site, 0)}
    predS = predict_serving_compiles(
        lg_workload, buckets=[8, 16], max_len=32, block_size=4,
        slo_ttft_ms=40.0, sanitize=True)
    assert predS == predict_serving_compiles(
        lg_workload, buckets=[8, 16], max_len=32, block_size=4,
        slo_ttft_ms=40.0), "sanitize must be a predictor no-op"
    assert deltaS == predS, (
        f"sanitize-phase recompile prediction drifted:\n"
        f"  predicted {predS}\n  observed  {deltaS}")
    print(f"   sanitize: lint_serving clean, "
          f"{sanS['lock_acquires']} sanitized acquires over "
          f"{sanS['locks_tracked']} locks ({sanS['order_edges']} "
          f"order edges), 0 cycles / 0 violations, goodput "
          f"{reportS['goodput_per_s']}/s ~ plain "
          f"{report['goodput_per_s']}/s, {deltaS} == predicted")

    # -- cancel/hedge phase: request lifecycle is host-side -----------
    # Cancellation is pure queue/slot surgery and a hedge clone lands
    # in the primary's already-warm prefill bucket, so a fresh phase
    # (the sanitize finally bumped the flags version) that cancels at
    # the queued AND decode stages and races one real hedge must
    # retrace exactly what the plain workload would: the predictor
    # says ``cancel=``/``hedge=`` are no-ops and the live tracker must
    # agree. The race's loser is canceled leak-free, the shared
    # RetryBudget gauge goes live for the /metrics scrape below, and
    # the run log grows serving_cancel / serving_hedge events.
    import time as _time

    from paddle_tpu.serving import ReplicaRouter
    baseC = {site: c["count"]
             for site, c in observability.compiles().items()
             if site.startswith(("serving_", "decode_", "verify_"))}
    rtC = ReplicaRouter(model, n_replicas=2, max_slots=3, max_len=32,
                        buckets=[8, 16], max_queue=16, block_size=4,
                        hedge_ms=5.0)
    # deterministic straggler: replica 0 predicts slow (pinned prefill
    # cost) and IS slow (its first steps do nothing), so the hedge
    # fires after the 5 ms delay and the clone on replica 1 wins
    slowC = rtC.engines[0]
    slowC._prefill_ms_pin = 500.0
    _orig_stepC = slowC.step
    _skipC = {"n": 0}

    def _lazy_stepC():
        _skipC["n"] += 1
        if _skipC["n"] <= 8:
            return False
        return _orig_stepC()
    slowC.step = _lazy_stepC
    rh = rtC.submit([1, 2, 3, 4], max_new_tokens=4)
    _time.sleep(0.01)        # let the hedge delay lapse
    for _ in range(400):
        rtC.step()
        if rh.done:
            break
    assert rh.state == "done", (rh.state, rh.error)
    slowC.step = _orig_stepC
    slowC._prefill_ms_pin = 0.0
    hstC = rtC.stats()["hedges"]
    assert hstC["fired"] == 1 and hstC["wins"] == 1, hstC
    r_q = rtC.submit([5, 6, 7, 8], max_new_tokens=4)
    outq = rtC.cancel(r_q.id)
    assert outq is not None and outq["stage"] == "queued", outq
    assert rtC.cancel(r_q.id) is None   # double-cancel: no-op
    r_d = rtC.submit([2, 3, 4, 5], max_new_tokens=8)
    for _ in range(400):
        rtC.step()
        if r_d.first_token_at is not None:
            break
    outd = rtC.cancel(r_d.id, reason="client")
    assert outd is not None and outd["stage"] == "decode", outd
    rtC.run_until_idle()
    for e in rtC.engines:
        e.cache.flush_prefix_cache()
        assert e.cache.allocator.leaked() == 1, (  # trash block only
            e.cache.allocator.leaked())
    cstC = rtC.stats()["canceled"]
    assert cstC.get("hedge_lose") == 1 and cstC.get("client") == 2, cstC
    afterC = {site: c["count"]
              for site, c in observability.compiles().items()
              if site.startswith(("serving_", "decode_", "verify_"))}
    deltaC = {site: n - baseC.get(site, 0)
              for site, n in afterC.items() if n - baseC.get(site, 0)}
    burstC = [[([1, 2, 3, 4], 4), ([2, 3, 4, 5], 8)]]
    predC = predict_serving_compiles(
        burstC, buckets=[8, 16], max_len=32, block_size=4,
        n_replicas=2, cancel=3, hedge=1)
    assert predC == predict_serving_compiles(
        burstC, buckets=[8, 16], max_len=32, block_size=4,
        n_replicas=2), "cancel/hedge must be predictor no-ops"
    assert deltaC == predC, (
        f"cancel/hedge-phase recompile prediction drifted:\n"
        f"  predicted {predC}\n  observed  {deltaC}")
    from paddle_tpu.resilience.retry import default_budget
    assert default_budget().remaining() > 0
    print(f"   cancel/hedge: hedge fired+won, canceled {cstC} "
          f"(queued + mid-decode + hedge loser), 0 leaked blocks, "
          f"retry budget {default_budget().remaining():.1f} tokens, "
          f"{deltaC} == predicted")

    # -- host-tier phase: session parking is host-side numpy ----------
    # Enabling the host KV tier bumps the flags version (a fresh
    # phase), but every demotion/promotion is host-side numpy surgery:
    # the predictor says ``host_tier=``/``sessions=`` are validated
    # no-ops and the live tracker must agree. A two-turn session is
    # demoted off device by the idle sweep, resumed from host RAM, and
    # the resumed turn must be token-identical to replaying the stored
    # conversation as a plain prompt. Both tiers drain leak-free.
    from paddle_tpu.serving.kv_tier import HostBlockStore, TierManager
    baseT = {site: c["count"]
             for site, c in observability.compiles().items()
             if site.startswith(("serving_", "decode_", "verify_"))}
    pt.set_flags({"serving_host_tier": True, "serving_host_blocks": 64})
    storeT = HostBlockStore(cfg.num_layers, cfg.num_heads, cfg.head_dim,
                            block_size=4, num_blocks=64)
    tierT = TierManager(storeT, demote_idle_ms=0.0)
    engT = ServingEngine(model, max_slots=2, max_len=32,
                         buckets=[8, 16], max_queue=16, block_size=4,
                         kv_tier=tierT)
    # round 1 warms BOTH prefill buckets, so the resume suffix lands
    # warm no matter how much of the context promotion covers
    tT1 = [3, 1, 4]
    fillT = [2, 7, 1, 8, 2, 8, 1, 8, 2, 8]
    rT1 = engT.submit(tT1, max_new_tokens=4, session="obs")
    rF = engT.submit(fillT, max_new_tokens=4)
    engT.run_until_idle()
    assert rT1.state == "done" and rF.state == "done"
    for _ in range(3):          # idle sweep demotes the cold chains
        engT.step()
    stT = tierT.stats()
    assert stT["sessions_host"] == 1, stT
    assert stT["migrated_demote_blocks"] > 0, stT
    tT2 = [1, 5]
    rT2 = engT.submit(tT2, max_new_tokens=4, session="obs")
    engT.run_until_idle()
    assert rT2.state == "done"
    stT = tierT.stats()
    assert stT["sessions_resumed"] == 1, stT
    assert stT["migrated_promote_blocks"] > 0, stT
    # token identity: the resumed turn equals replaying the stored
    # conversation (turn-1 full sequence + turn-2 prompt) sessionless
    ctxT = rT1.output_ids + tT2
    rT3 = engT.submit(ctxT, max_new_tokens=4)
    engT.run_until_idle()
    assert rT3.state == "done" and rT3.output_ids == rT2.output_ids, (
        rT3.output_ids, rT2.output_ids)
    engT.run_until_idle()
    engT.cache.flush_prefix_cache()
    assert engT.cache.allocator.leaked() == 1, (  # trash block only
        engT.cache.allocator.leaked())
    tierT.flush()
    assert tierT.leaked() == 0, tierT.leaked()
    afterT = {site: c["count"]
              for site, c in observability.compiles().items()
              if site.startswith(("serving_", "decode_", "verify_"))}
    deltaT = {site: n - baseT.get(site, 0)
              for site, n in afterT.items() if n - baseT.get(site, 0)}
    workloadT = [[(tT1, 4), (fillT, 4)], [(ctxT, 4)], [(ctxT, 4)]]
    predT = predict_serving_compiles(
        workloadT, buckets=[8, 16], max_len=32, block_size=4,
        host_tier=True, sessions=1)
    assert predT == predict_serving_compiles(
        workloadT, buckets=[8, 16], max_len=32, block_size=4), \
        "host_tier/sessions must be predictor no-ops"
    assert deltaT == predT, (
        f"host-tier-phase recompile prediction drifted:\n"
        f"  predicted {predT}\n  observed  {deltaT}")
    print(f"   host tier: demote {stT['migrated_demote_blocks']} / "
          f"promote {stT['migrated_promote_blocks']} blocks, resume "
          f"token-identical, 0 leaks both tiers, {deltaT} == predicted")

    # -- megastep phase: device-resident decode megasteps -------------
    # FLAGS_serving_megastep=N runs N decode iterations inside ONE
    # compiled dispatch (lax.scan carrying the paged pools, early-exit
    # state as data) and FLAGS_serving_dispatch_ahead enqueues
    # megastep k+1 against the un-synced carries while k executes.
    # The decode plane has exactly TWO compile surfaces under N > 1:
    # decode_megastep_paged{n=N}, plus the single-token fallback the
    # scheduler drops to whenever a megastep is unsafe for the whole
    # batch — driven here by a request whose stop list exceeds the
    # device stop-table caps. This burst exercises both, tokens must
    # equal a megastep=1 engine's at the same flags version (which
    # itself adds ZERO compiles: the fallback already retraced
    # decode_step_paged), and the per-phase delta must equal
    # predict_serving_compiles(megastep=4).
    from paddle_tpu.serving.decoding import STOP_MAX_SEQS
    baseM = {site: c["count"]
             for site, c in observability.compiles().items()
             if site.startswith(("serving_", "decode_", "verify_"))}
    pt.set_flags({"serving_megastep": 4,
                  "serving_dispatch_ahead": True,
                  "serving_host_tier": False})
    try:
        engM = ServingEngine(model, max_slots=3, max_len=32,
                             buckets=[8, 16], max_queue=16,
                             block_size=4)
        big_stops = [[90 + j] for j in range(STOP_MAX_SEQS + 1)]
        reqsM = [engM.submit(p, max_new_tokens=8) for p in prompts]
        reqsM.append(engM.submit(prompts[0], max_new_tokens=8,
                                 stop=big_stops))
        engM.run_until_idle()
        assert all(r.state == "done" for r in reqsM)
        stM = engM.stats()
        assert stM["megastep"] == 4 and stM["dispatch_ahead"], stM
        assert stM["ahead_hits"] + stM["ahead_misses"] >= 1, stM
        eng1 = ServingEngine(model, max_slots=3, max_len=32,
                             buckets=[8, 16], max_queue=16,
                             block_size=4, megastep=1,
                             dispatch_ahead=False)
        reqs1 = [eng1.submit(p, max_new_tokens=8) for p in prompts]
        reqs1.append(eng1.submit(prompts[0], max_new_tokens=8,
                                 stop=big_stops))
        eng1.run_until_idle()
        for a, b in zip(reqsM, reqs1):
            assert a.output_ids == b.output_ids, (
                f"megastep=4 diverged on request {a.id}: "
                f"{a.output_ids} vs {b.output_ids}")
        engM.cache.flush_prefix_cache()
        assert engM.cache.allocator.leaked() == 1  # trash block only
        afterM = {site: c["count"]
                  for site, c in observability.compiles().items()
                  if site.startswith(("serving_", "decode_",
                                      "verify_"))}
        deltaM = {site: n - baseM.get(site, 0)
                  for site, n in afterM.items()
                  if n - baseM.get(site, 0)}
        workloadM = [[(p, 8) for p in prompts] + [(prompts[0], 8)]]
        predM = predict_serving_compiles(
            workloadM, buckets=[8, 16], max_len=32, block_size=4,
            megastep=4)
        assert deltaM == predM, (
            f"megastep-phase recompile prediction drifted:\n"
            f"  predicted {predM}\n  observed  {deltaM}")
        print(f"   megastep: N=4 + dispatch-ahead token-identical to "
              f"N=1 ({stM['ahead_hits']} ahead hits / "
              f"{stM['ahead_misses']} misses), both decode surfaces "
              f"traced, {deltaM} == predicted")
    finally:
        pt.set_flags({"serving_megastep": 1,
                      "serving_dispatch_ahead": False})

    # -- devprof phase: the device-cost observatory is a validated ----
    # no-op. FLAGS_serving_devprof bumps the flags version (a fresh
    # phase), then every compile's XLA cost_analysis is captured by an
    # out-of-band lowering of the RAW function — so the per-phase
    # delta must equal the PLAIN prediction and
    # predict_serving_compiles(devprof=True) must agree devprof never
    # compiles. Sampling at 1.0 on the wall clock, every dispatch pays
    # one block_until_ready: the cost table fills, the roofline/MFU
    # gauges go live for the /metrics scrape below, every traced
    # request's blame decomposes decode into decode_device +
    # decode_host with the reconciliation identity intact, and
    # /v1/stats serves the devprof section.
    from paddle_tpu.observability import devprof
    tracing.reset()
    baseD = {site: c["count"]
             for site, c in observability.compiles().items()
             if site.startswith(("serving_", "decode_", "verify_"))}
    pt.set_flags({"serving_devprof": True})
    try:
        engD = ServingEngine(model, max_slots=3, max_len=32,
                             buckets=[8, 16], max_queue=16,
                             block_size=4, devprof_sample=1.0)
        reqsD = [engD.submit(p, max_new_tokens=4) for p in prompts]
        engD.run_until_idle()
        assert all(r.state == "done" for r in reqsD)
    finally:
        pt.set_flags({"serving_devprof": False})
    stD = engD.stats()["devprof"]
    assert stD["sample"] == 1.0, stD
    assert stD["dispatches"] > 0 and \
        stD["samples"] == stD["dispatches"], stD
    assert stD["device_frac"] is not None, stD
    assert any(e["entry"] == "decode_step_paged"
               for e in stD["entries"]), stD
    costsD = devprof.cost_table()
    assert "decode_step_paged" in costsD, sorted(costsD)
    assert devprof.cost_digest(), costsD
    if devprof.cost_analysis_supported():
        cD = costsD["decode_step_paged"]
        assert cD["flops"] and cD["hbm_bytes"], cD
        assert stD["mfu"] is not None and stD["mfu"] > 0.0, stD
    for r in reqsD:
        infoD = tracing.get(r.id)
        bl = infoD["blame_ms"]
        assert "decode" not in bl and "decode_device" in bl and \
            "decode_host" in bl, bl
        gapD = abs(sum(bl.values()) - infoD["e2e_ms"])
        assert gapD < 1e-6, (
            f"devprof blame split broke the identity on request "
            f"{r.id}: {bl} vs e2e {infoD['e2e_ms']} (gap {gapD})")
    afterD = {site: c["count"]
              for site, c in observability.compiles().items()
              if site.startswith(("serving_", "decode_", "verify_"))}
    deltaD = {site: n - baseD.get(site, 0)
              for site, n in afterD.items() if n - baseD.get(site, 0)}
    burstD = [[(p, 4) for p in prompts]]
    predD = predict_serving_compiles(
        burstD, buckets=[8, 16], max_len=32, block_size=4,
        devprof=True)
    assert predD == predict_serving_compiles(
        burstD, buckets=[8, 16], max_len=32, block_size=4), \
        "devprof must be a predictor no-op"
    assert deltaD == predD, (
        f"devprof-phase recompile prediction drifted:\n"
        f"  predicted {predD}\n  observed  {deltaD}")
    srvD = ServingHTTPServer(engD, port=0)
    srvD.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srvD.port}/v1/stats",
                timeout=10) as r:
            assert r.status == 200
            statsD = json.loads(r.read().decode())
        assert statsD["devprof"]["samples"] == stD["samples"], statsD
    finally:
        srvD.stop()
    print(f"   devprof: {stD['samples']}/{stD['dispatches']} dispatches "
          f"sampled, device_frac {stD['device_frac']}, mfu "
          f"{stD['mfu']}, {len(costsD)} costed sites (digest "
          f"{devprof.cost_digest()}), blame split exact, "
          f"{deltaD} == predicted (ZERO devprof compiles)")

    # -- /metrics scrape ----------------------------------------------
    srv = ServingHTTPServer(eng, port=0)
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
    finally:
        srv.stop()
    n = observability.validate_prometheus_text(text)
    for needle in ("STAT_serving_tokens", "STAT_fault_exec_step",
                   "STAT_guardian_skipped", "xla_compiles",
                   "serving_ttft_seconds", "serving_kv_blocks_used",
                   "serving_kv_blocks_free", "STAT_serving_prefix_hits",
                   "serving_attn_impl", "serving_kv_dequant_max_abs_err",
                   "STAT_serving_kv_quant_writes", "serving_mesh_devices",
                   "serving_replicas", "serving_queue_depth",
                   "serving_slo_attainment", "serving_shed_total",
                   "serving_weight_version",
                   "serving_prefix_affinity_hits",
                   "serving_handoff_queue_depth",
                   "serving_disagg_workers",
                   "serving_lora_adapters_loaded",
                   "STAT_serving_lora_loads",
                   "serving_replica_state",
                   "serving_rehomed_total",
                   "STAT_serving_rehomed",
                   "serving_traced_total",
                   "sanitizer_lock_acquires",
                   "serving_canceled_total",
                   "serving_hedges_total",
                   "serving_retry_budget_remaining",
                   "serving_kv_migrations",
                   "serving_sessions_resident",
                   "serving_sessions_host",
                   "serving_sessions_resumed",
                   "xla_cost",
                   "serving_device_step_ms",
                   "serving_mfu",
                   "serving_hbm_util",
                   "serving_host_overhead_share"):
        assert needle in text, f"/metrics missing {needle}"
    print(f"   /metrics: {n} samples, valid Prometheus text")

    # -- run log consumed by trace_summary ----------------------------
    runlog.close()
    path = os.path.join(tmp, f"runlog-{os.getpid()}.jsonl")
    kinds = set()
    with open(path) as f:
        for line in f:
            kinds.add(json.loads(line)["kind"])
    for k in ("train_step", "guardian_skip", "fault_injected",
              "serving_admit", "serving_finish", "serving_weight_swap",
              "serving_request", "serving_handoff",
              "serving_lora_load", "serving_replica_kill",
              "serving_replica_recover", "serving_cancel",
              "serving_hedge", "serving_kv_demote",
              "serving_kv_promote", "serving_session_resume",
              "serving_megastep"):
        assert k in kinds, f"run log missing {k!r} events (got {kinds})"
    from tools import trace_summary
    rc = trace_summary.main([path, "--top", "5"])
    assert rc == 0
    print(f"   run log: {sorted(kinds)} -> trace_summary ok")
    print("observability gate PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Op-registry compat check: dump every registered op's grad contract.

Analog of the reference's tools/check_op_desc.py (CI guard against
incompatible op changes). Dumps op type -> differentiability + slot
metadata as JSON; diff two dumps to catch silently-breaking registry
changes.

    python tools/check_op_desc.py > ops.json
    python tools/check_op_desc.py --diff ops.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def dump_ops() -> dict:
    from paddle_tpu.ops import registry as reg
    out = {}
    for name in reg.registered_ops():
        d = reg.get_op_def(name)
        out[name] = {
            "not_differentiable": d.not_differentiable,
            "no_grad_slots": sorted(d.no_grad_slots),
            "nondiff_outputs": sorted(d.nondiff_outputs),
            "grad_drops_inputs": sorted(d.grad_drops_inputs),
            "grad_needs_outputs": sorted(d.grad_needs_outputs),
            "custom_grad": d.custom_grad_maker is not None,
            "version": d.version,
        }
    return out


def main(argv=None):
    p = argparse.ArgumentParser("check_op_desc")
    p.add_argument("--diff", help="baseline JSON to compare against")
    args = p.parse_args(argv)
    ops = dump_ops()
    if not args.diff:
        json.dump(ops, sys.stdout, indent=1, sort_keys=True)
        print()
        return 0
    with open(args.diff) as f:
        base = json.load(f)
    removed = sorted(set(base) - set(ops))
    added = sorted(set(ops) - set(base))

    def _norm(d):
        # keep old baselines usable: fields added to the dump format
        # since the baseline was generated get their defaults, instead
        # of flagging every op as CHANGED
        out = dict(d)
        out.setdefault("version", 1)
        return out

    changed = sorted(k for k in set(base) & set(ops)
                     if _norm(base[k]) != _norm(ops[k]))
    for kind, names in (("REMOVED", removed), ("CHANGED", changed)):
        for n in names:
            print(f"{kind}: {n}")
    for n in added:
        print(f"added: {n}")
    if removed or changed:
        print(f"\nINCOMPATIBLE: {len(removed)} removed, "
              f"{len(changed)} changed (additions are fine)")
        return 1
    print(f"OK: {len(ops)} ops, {len(added)} new")
    return 0


if __name__ == "__main__":
    sys.exit(main())

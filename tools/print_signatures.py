#!/usr/bin/env python
"""API-freeze check: print every public API signature, hashed.

Analog of the reference's tools/print_signatures.py (the CI approval
check that flags any public-API signature change). Usage:

    python tools/print_signatures.py paddle_tpu > api.spec
    # ... after changes ...
    python tools/print_signatures.py paddle_tpu | diff api.spec -
"""

from __future__ import annotations

import hashlib
import importlib
import inspect
import os
import pkgutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _signature(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def iter_api(root_name: str):
    root = importlib.import_module(root_name)
    seen_modules = {root_name}
    modules = [root]
    if hasattr(root, "__path__"):
        for info in pkgutil.walk_packages(root.__path__,
                                          prefix=root_name + "."):
            if info.name in seen_modules:
                continue
            # built native artifacts (_<name>-<srchash>-<flaghash>.so)
            # carry content hashes in their filenames — they are build
            # outputs, not API surface, and would churn the snapshot on
            # every C++ edit
            if info.name.rsplit(".", 1)[-1].startswith("_"):
                continue
            seen_modules.add(info.name)
            try:
                modules.append(importlib.import_module(info.name))
            except Exception as e:  # report broken modules, don't crash
                yield info.name, f"<import error: {type(e).__name__}>"
    for mod in modules:
        public = getattr(mod, "__all__", None)
        if public is None:
            public = [n for n in vars(mod) if not n.startswith("_")]
        for name in sorted(public):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            qual = f"{mod.__name__}.{name}"
            if inspect.isclass(obj):
                yield qual, f"class{_signature(obj)}"
                for mname, m in sorted(vars(obj).items()):
                    if mname.startswith("_") and mname != "__init__":
                        continue
                    if inspect.isfunction(m):
                        yield f"{qual}.{mname}", _signature(m)
            elif callable(obj):
                yield qual, _signature(obj)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else "paddle_tpu"
    for qual, sig in sorted(iter_api(root)):
        digest = hashlib.md5(sig.encode()).hexdigest()[:10]
        print(f"{qual} {digest} {sig}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

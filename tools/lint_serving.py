#!/usr/bin/env python
"""Static concurrency + resource-lifecycle lint over the serving modules.

Runs ``paddle_tpu.analysis.lifecycle`` over the serving fleet sources
(`engine.py`, `router.py`, `disagg.py`, `kv_cache.py`, `lora.py`):

    python tools/lint_serving.py --strict
    python tools/lint_serving.py --json
    python tools/lint_serving.py path/to/extra.py --no-default-paths

Two checkers (see the module docstring for the full semantics):

- **resource-leak / double-release / release-after-move** — dataflow
  over KV/LoRA obligations (``acquire``/``import_row``/``adopt_row``
  create, ``release*``/``deref`` discharge, ``export_row`` moves),
  proving release-on-all-paths including raise edges and shed
  branches, with a path witness per finding;
- **unguarded-write** — writes to ``# guarded-by: <lock>`` attributes
  outside ``with self.<lock>:`` (or a ``# holds: <lock>`` method).

Accepted findings live in ``tools/lint_serving_baseline.json``
(``{"entries": [{"key": ..., "justification": ...}]}``); every entry
must carry a one-line justification, and stale entries are warnings.

Exit status 1 on ERROR findings; --strict also fails on warnings.
Pure stdlib AST analysis — no JAX import, safe anywhere.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "lint_serving_baseline.json")


def main(argv=None):
    ap = argparse.ArgumentParser(
        "lint_serving",
        description="Static lifecycle + lock-discipline checks over "
                    "the serving modules.")
    ap.add_argument("paths", nargs="*",
                    help="extra source files to lint (on top of the "
                         "serving modules unless --no-default-paths)")
    ap.add_argument("--no-default-paths", action="store_true",
                    help="lint only the paths given on the command "
                         "line")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="justified-findings baseline JSON "
                         "[tools/lint_serving_baseline.json]; "
                         "'' disables")
    ap.add_argument("--strict", action="store_true",
                    help="treat warnings as fatal too")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON report on stdout instead of "
                         "text")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import lifecycle

    if args.no_default_paths:
        if not args.paths:
            raise SystemExit(
                "--no-default-paths needs explicit paths")
        paths = list(args.paths)
    else:
        here = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "paddle_tpu", "serving")
        paths = [os.path.join(here, f)
                 for f in lifecycle.SERVING_FILES]
        paths += list(args.paths)

    result = lifecycle.lint_files(paths)
    baseline = {}
    if args.baseline and os.path.exists(args.baseline):
        baseline = lifecycle.load_baseline(args.baseline)
        result = lifecycle.apply_baseline(result, baseline)
    failed = bool(result.errors) or (args.strict
                                     and bool(result.warnings))

    if args.as_json:
        print(json.dumps({
            "ok": not failed,
            "files": [os.path.basename(p) for p in paths],
            "errors": len(result.errors),
            "warnings": len(result.warnings),
            "baselined": len(result.baselined),
            "diagnostics": [dataclasses.asdict(d)
                            for d in result.diagnostics],
            "baselined_keys": sorted({d.key
                                      for d in result.baselined}),
        }, indent=2))
        return 1 if failed else 0

    print(f"serving lint: {len(paths)} file(s), "
          f"{len(baseline)} baseline entr(ies)")
    for d in result.diagnostics:
        print(f"  {d}")
    for d in result.baselined:
        print(f"  [baselined] {d.key}: {baseline.get(d.key, '')}")
    print(f"{'FAIL' if failed else 'ok'}: {len(result.errors)} "
          f"error(s), {len(result.warnings)} warning(s), "
          f"{len(result.baselined)} baselined")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

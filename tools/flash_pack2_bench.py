#!/usr/bin/env python
"""Head-packing experiment for the d=64 flash-attention MXU ceiling.

PERF.md's decomposition: with head_dim 64, both attention matmuls
contract/emit over 64 of the MXU's 128 lanes — a structural ~50%
ceiling on the matmul portion (GPT-2 geometry). Hypothesis: pack TWO
heads per kernel instance — q rides as [bq, 128] (head pair
concatenated along d) and k/v blocks expand to BLOCK-DIAGONAL
[2*bk, 128] so that

    s2  = q  @ K_bd^T -> [bq, 2*bk]   (both heads' logits, one pass)
    acc = p2 @ V_bd   -> [bq, 128]    (both heads' outputs, one pass)

every MXU pass contracts and emits the full 128 lanes. Half the MACs
multiply zeros, so the FLOP count doubles — the bet is that a
64-contraction pass already costs a full pass, making the packed form
2x on paper. The online softmax segments per head ([bq, 2, bk] view).

Forward-only: this is a measurement probe (VERDICT round-4 item 7); if
it wins, the packed layout graduates into ops/pallas/flash_attention
with a backward. Run on the real chip:

    python tools/flash_pack2_bench.py          # prints one JSON line

Amortizes with an in-graph lax.scan chain (the axon tunnel's ~100 ms
dispatch would otherwise dominate; see memory notes / PERF.md).
"""

import functools
import json
import math
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from paddle_tpu.ops.pallas.flash_attention import _flash_fwd  # noqa: E402
from paddle_tpu.ops.pallas.utils import interpret_mode  # noqa: E402

NEG_INF = float("-inf")


def _packed_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal,
                       block_k, seq_k):
    block_q, d2 = q_ref.shape[1], q_ref.shape[2]      # d2 = 128
    d = d2 // 2
    jq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    hi = jnp.minimum((jq + 1) * block_q + block_k - 1, seq_k) // block_k \
        if causal else pl.cdiv(seq_k, block_k)

    def body(kb, carry):
        # m/l carried per head half as [bq, 1] (Mosaic-friendly: no
        # repeat/reshape layout casts)
        m1, m2, l1, l2, acc = carry
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(
            jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(
            jnp.float32)
        z = jnp.zeros((block_k, d), jnp.float32)
        # block-diagonal packing: rows 0..bk are head-1, bk.. head-2
        k_bd = jnp.concatenate(
            [jnp.concatenate([kblk[:, :d], z], 1),
             jnp.concatenate([z, kblk[:, d:]], 1)], 0)   # [2bk, 128]
        v_bd = jnp.concatenate(
            [jnp.concatenate([vblk[:, :d], z], 1),
             jnp.concatenate([z, vblk[:, d:]], 1)], 0)
        s2 = jax.lax.dot_general(q, k_bd, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if causal:
            row = jq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 2 * block_k), 0)
            col = kb * block_k + jnp.mod(jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 2 * block_k), 1), block_k)
            s2 = jnp.where(row >= col, s2, NEG_INF)
        s_a = s2[:, :block_k]
        s_b = s2[:, block_k:]
        m1n = jnp.maximum(m1, jnp.max(s_a, axis=1, keepdims=True))
        m2n = jnp.maximum(m2, jnp.max(s_b, axis=1, keepdims=True))
        a1 = jnp.exp(m1 - m1n)
        a2 = jnp.exp(m2 - m2n)
        p_a = jnp.exp(s_a - m1n)
        p_b = jnp.exp(s_b - m2n)
        l1n = a1 * l1 + jnp.sum(p_a, axis=1, keepdims=True)
        l2n = a2 * l2 + jnp.sum(p_b, axis=1, keepdims=True)
        scaled = jnp.concatenate([acc[:, :d] * a1, acc[:, d:] * a2], 1)
        acc = scaled + jax.lax.dot_general(
            jnp.concatenate([p_a, p_b], 1), v_bd,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m1n, m2n, l1n, l2n, acc

    neg = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    zero = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d2), jnp.float32)
    m1, m2, l1, l2, acc = jax.lax.fori_loop(
        0, hi, body, (neg, neg, zero, zero, acc0))
    o_ref[0] = jnp.concatenate([acc[:, :d] / l1, acc[:, d:] / l2],
                               1).astype(o_ref.dtype)


def packed_flash_fwd(q, k, v, causal, scale, block_q, block_k):
    """q/k/v [bh2, s, 128] (head pairs concatenated along d)."""
    bh2, seq_q, d2 = q.shape
    seq_k = k.shape[1]
    kernel = functools.partial(_packed_fwd_kernel, scale=scale,
                               causal=causal, block_k=block_k,
                               seq_k=seq_k)
    return pl.pallas_call(
        kernel,
        grid=(bh2, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d2), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, seq_k, d2), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, seq_k, d2), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d2), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret_mode(),
    )(q, k, v)


def pack_pairs(x):
    """[b, h, s, d] -> [b*h/2, s, 2d] (adjacent head pairs)."""
    b, h, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h // 2, 2, s, d), 2, 3).reshape(
        b * h // 2, s, 2 * d)


def _time_scan(fn, args, iters=50):
    """In-graph scan chain, scalar fetch (tunnel-safe timing)."""

    def chained(a):
        def step(carry, _):
            out = fn(*[x + carry * 0 for x in a])
            return jnp.sum(out) * 1e-12, None
        s, _ = jax.lax.scan(step, jnp.float32(0), None, length=iters)
        return s

    f = jax.jit(chained)
    float(f(args))                      # compile + warm
    t0 = time.perf_counter()
    float(f(args))
    dt = time.perf_counter() - t0
    return dt / iters


def main():
    b, h, s, d = 8, 16, 1024, 64
    bq = bk = 512
    scale = 1.0 / math.sqrt(d)
    rng = np.random.RandomState(0)
    qkv = [jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
           for _ in range(3)]
    q3 = [x.reshape(b * h, s, d) for x in qkv]
    qp = [pack_pairs(x) for x in qkv]

    # numerical check (fp32, interpreter-safe shapes)
    o_ref, _ = _flash_fwd(*[x.astype(jnp.float32) for x in q3], True,
                          scale, bq, bk)
    o_pk = packed_flash_fwd(*[x.astype(jnp.float32) for x in qp], True,
                            scale, bq, bk)
    o_pk_un = jnp.swapaxes(
        o_pk.reshape(b, h // 2, s, 2, d), 2, 3).reshape(b * h, s, d)
    err = float(jnp.max(jnp.abs(o_ref - o_pk_un)))
    assert err < 2e-3, f"packed kernel numerics off: {err}"

    t_base = _time_scan(
        lambda q, k, v: _flash_fwd(q, k, v, True, scale, bq, bk)[0], q3)
    t_pack = _time_scan(
        lambda q, k, v: packed_flash_fwd(q, k, v, True, scale, bq, bk),
        qp)
    print(json.dumps({
        "metric": "flash_fwd_pack2_speedup",
        "value": round(t_base / t_pack, 3), "unit": "x",
        "base_ms": round(t_base * 1e3, 3),
        "packed_ms": round(t_pack * 1e3, 3),
        "shape": [b, h, s, d], "blocks": [bq, bk],
        "max_abs_err": err,
        "device": getattr(jax.devices()[0], "device_kind", "cpu"),
    }))


if __name__ == "__main__":
    main()

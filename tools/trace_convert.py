#!/usr/bin/env python
"""Convert a run log into a replayable loadgen trace.

Every serving front door (``ServingEngine.submit``, ``ReplicaRouter``,
``DisaggRouter``) logs one ``serving_request`` event per arrival —
``t`` (engine-clock seconds), ``prompt``, ``max_new_tokens``,
``priority``. This tool filters those events out of a runlog JSONL
file (``FLAGS_runlog_dir/runlog-<pid>.jsonl``), re-bases time so the
first arrival lands at t=0, and emits the trace format
``tools/loadgen.py --replay`` / ``LoadGen.from_trace`` consume::

    {"meta": {"source": ..., "duration": ..., "rate": ...},
     "arrivals": [[t, prompt, max_new_tokens, priority], ...],
     "chaos": [[t, kind, index], ...]}       # when the run had any

Chaos events ride along: ``serving_replica_kill`` /
``serving_replica_recover`` / ``serving_worker_kill`` events become
``chaos`` rows (kind kill | restart | kill_decode | kill_prefill) on
the same re-based clock — a kill+recover pair at one instant collapses
into a single ``restart`` — so a live soak's kill/restart schedule
replays deterministically alongside its arrivals
(``LoadGen.run`` fires each row when the clock passes its ``t``).

So a production incident captured in the run log replays — same
prompts, same spacing — against any engine/fleet configuration::

    python tools/trace_convert.py /tmp/runlog/runlog-1234.jsonl \
        -o incident.json
    python tools/loadgen.py --replay incident.json --disagg 1x2 \
        --virtual-step-ms 5 --json

Rotated siblings (``.jsonl.1``) can be passed alongside the active
file; events merge and sort by (t, seq) regardless of file order.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List, Optional


def events_to_trace(events: Iterable[dict],
                    source: Optional[str] = None,
                    engine: Optional[str] = None) -> Dict:
    """Build a replayable trace from parsed runlog event dicts.

    Keeps only ``kind == "serving_request"`` events (optionally those
    whose ``engine``/``router`` label equals ``engine``), sorts by
    (t, seq) so interleaved producers land in arrival order, and
    re-bases ``t`` to the first kept arrival.
    """
    _CHAOS_KINDS = ("serving_replica_kill", "serving_replica_recover",
                    "serving_worker_kill")
    kept, chaos_evs = [], []
    for ev in events:
        kind = ev.get("kind")
        if kind in _CHAOS_KINDS and "t" in ev:
            chaos_evs.append(ev)
            continue
        if kind != "serving_request":
            continue
        if engine is not None and \
                ev.get("engine", ev.get("router")) != engine:
            continue
        kept.append(ev)
    kept.sort(key=lambda ev: (float(ev["t"]), int(ev.get("seq", 0))))
    t0 = float(kept[0]["t"]) if kept else 0.0
    arrivals: List[list] = []
    for ev in kept:
        arrivals.append([round(float(ev["t"]) - t0, 6),
                         [int(x) for x in ev["prompt"]],
                         int(ev["max_new_tokens"]),
                         int(ev.get("priority", 1))])
    # chaos rows share the arrivals' clock; a kill immediately
    # followed by a recover of the same replica is one restart
    chaos_evs.sort(key=lambda ev: (float(ev["t"]),
                                   int(ev.get("seq", 0))))
    recovered = {(int(ev["replica"]), round(float(ev["t"]), 6))
                 for ev in chaos_evs
                 if ev["kind"] == "serving_replica_recover"}
    chaos: List[list] = []
    for ev in chaos_evs:
        t = round(float(ev["t"]) - t0, 6)
        if ev["kind"] == "serving_replica_recover":
            chaos.append([t, "restart", int(ev["replica"])])
        elif ev["kind"] == "serving_replica_kill":
            if (int(ev["replica"]),
                    round(float(ev["t"]), 6)) in recovered:
                continue   # folded into the restart row
            chaos.append([t, "kill", int(ev["replica"])])
        else:   # serving_worker_kill
            role = ev.get("role", "decode")
            chaos.append([t, f"kill_{role}", int(ev["worker"])])
    duration = arrivals[-1][0] if arrivals else 0.0
    meta: Dict = {"events": len(arrivals), "duration": duration}
    if duration > 0:
        meta["rate"] = round(len(arrivals) / duration, 6)
    if source:
        meta["source"] = source
    if engine:
        meta["engine"] = engine
    out = {"meta": meta, "arrivals": arrivals}
    if chaos:
        out["chaos"] = chaos
    return out


def load_events(paths: Iterable[str]) -> List[dict]:
    """Parse runlog JSONL files; blank and truncated trailing lines
    (a live writer mid-append) are skipped, not fatal."""
    events: List[dict] = []
    for path in paths:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return events


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="turn runlog serving_request events into a "
                    "replayable loadgen trace")
    ap.add_argument("runlog", nargs="+",
                    help="runlog JSONL file(s); rotated .1 siblings "
                    "merge in sorted by time")
    ap.add_argument("-o", "--out", default="",
                    help="write the trace JSON here (default stdout)")
    ap.add_argument("--engine", default=None,
                    help="keep only events from this engine/router "
                    "label")
    args = ap.parse_args(argv)

    trace = events_to_trace(load_events(args.runlog),
                            source=",".join(args.runlog),
                            engine=args.engine)
    if not trace["arrivals"]:
        print("no serving_request events found", file=sys.stderr)
        return 1
    payload = json.dumps(trace, separators=(",", ":"))
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
        print(f"{trace['meta']['events']} arrivals over "
              f"{trace['meta']['duration']:.3f}s -> {args.out}")
    else:
        print(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Summarize a profiler chrome-trace JSON or an observability JSONL
run log as a top-N table.

    python tools/trace_summary.py /tmp/profile            # chrome trace
    python tools/trace_summary.py /tmp/runlog/runlog-1.jsonl
    python tools/trace_summary.py TRACE --top 20 --sort calls
    python tools/trace_summary.py /tmp/serving_trace.json --blame

Chrome traces (written by paddle_tpu.profiler.stop_profiler or
paddle_tpu.observability.tracing.export_chrome_trace) aggregate per
event name: calls, total ms, average ms. Run logs (written by
paddle_tpu.observability.log_event under FLAGS_runlog_dir) aggregate
per event kind: count, wall-clock span, and means of any numeric
fields (loss, step_time_ms, ttft_ms, ...) seen on that kind.

``--blame`` reads per-request serving spans instead — either a
tracing chrome trace (X events grouped by ``args.request``) or a
spans JSONL (``tracing.export_spans_jsonl``: one
``{"trace", "span", "t0", "t1", "dur_ms", ...}`` line per span) — and
prints the latency-component blame table: per-component total ms,
share of summed E2E, p95 ms, and which component dominates the E2E
p95 tail (see paddle_tpu/observability/tracing.py for the accounting
identity behind the numbers). Runs traced under FLAGS_serving_devprof
split ``decode`` into ``decode_device`` / ``decode_host`` rows and
carry embedded roofline entries (chrome ``devprof`` metadata events /
JSONL ``{"devprof": ...}`` lines); ``--blame`` then also prints the
per-compiled-entry roofline table with the verdict — compute-bound,
hbm-bound, or host-bound.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def load_events(path: str):
    """Returns ("chrome", events) or ("runlog", events). A chrome trace
    is one JSON document ({"traceEvents": [...]} or a bare event
    array); anything that only parses line by line is a JSONL run
    log."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        if "traceEvents" not in doc:
            raise SystemExit(
                f"{path}: JSON object without traceEvents — neither a "
                "chrome trace nor a JSONL run log")
        return "chrome", doc["traceEvents"]
    if isinstance(doc, list):
        return "chrome", doc
    events = []
    for ln, line in enumerate(text.splitlines(), start=1):
        line = line.strip()
        if not line:
            continue
        try:
            events.append(json.loads(line))
        except json.JSONDecodeError as e:
            raise SystemExit(f"{path}:{ln}: bad JSONL line: {e}")
    return "runlog", events


def summarize_chrome(events: List[dict]) -> List[dict]:
    agg: Dict[str, dict] = {}
    for e in events:
        if e.get("ph") not in (None, "X"):
            continue
        a = agg.setdefault(e.get("name", "?"),
                           {"name": e.get("name", "?"), "calls": 0,
                            "total_ms": 0.0})
        a["calls"] += 1
        a["total_ms"] += float(e.get("dur", 0.0)) / 1e3  # us -> ms
    for a in agg.values():
        a["avg_ms"] = a["total_ms"] / a["calls"]
    return list(agg.values())


def summarize_runlog(events: List[dict]) -> List[dict]:
    agg: Dict[str, dict] = {}
    for e in events:
        kind = e.get("kind", "?")
        a = agg.setdefault(kind, {"name": kind, "calls": 0,
                                  "mono_min": None, "mono_max": None,
                                  "fields": {}})
        a["calls"] += 1
        mono = e.get("mono")
        if isinstance(mono, (int, float)):
            a["mono_min"] = mono if a["mono_min"] is None else \
                min(a["mono_min"], mono)
            a["mono_max"] = mono if a["mono_max"] is None else \
                max(a["mono_max"], mono)
        for k, v in e.items():
            if k in ("seq", "ts", "mono", "kind"):
                continue
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                s = a["fields"].setdefault(k, [0, 0.0])
                s[0] += 1
                s[1] += v
    out = []
    for a in agg.values():
        span = (a["mono_max"] - a["mono_min"]
                if a["mono_min"] is not None else 0.0)
        means = {k: s[1] / s[0] for k, s in sorted(a["fields"].items())}
        out.append({"name": a["name"], "calls": a["calls"],
                    "total_ms": span * 1e3,
                    "avg_ms": span * 1e3 / a["calls"], "means": means})
    return out


def _pctl(vals: List[float], q: float) -> float:
    import math
    s = sorted(vals)
    idx = min(len(s) - 1,
              max(0, int(math.ceil(q / 100.0 * len(s))) - 1))
    return s[idx]


def collect_blame(fmt: str, events: List[dict]) -> Dict[int, dict]:
    """Group serving spans by request: chrome X events carry the
    request index in ``args.request`` (the tracing exporter), spans
    JSONL carries it as ``trace``. Returns
    {request: {"components": {name: ms}, "e2e_ms": float}}."""
    per: Dict[int, dict] = {}
    for e in events:
        if fmt == "chrome":
            if e.get("ph") != "X" or \
                    not isinstance(e.get("args"), dict) or \
                    "request" not in e["args"]:
                continue
            rid = e["args"]["request"]
            name = e.get("name", "?")
            dur = float(e.get("dur", 0.0)) / 1e3        # us -> ms
        else:
            if "span" not in e or "trace" not in e:
                continue
            rid = e["trace"]
            name = e["span"]
            dur = float(e.get("dur_ms",
                              (e.get("t1", 0.0) - e.get("t0", 0.0))
                              * 1e3))
        r = per.setdefault(rid, {"components": {}, "e2e_ms": 0.0})
        r["components"][name] = r["components"].get(name, 0.0) + dur
        r["e2e_ms"] += dur
    return per


def collect_devprof(fmt: str, events: List[dict]) -> List[dict]:
    """Device-cost observatory roofline rows embedded in a tracing
    export (FLAGS_serving_devprof): chrome metadata events named
    ``devprof``, or bare ``{"devprof": {...}}`` JSONL lines. Empty
    list when the run profiled nothing."""
    out = []
    for e in events:
        if fmt == "chrome":
            if e.get("ph") == "M" and e.get("name") == "devprof" and \
                    isinstance(e.get("args"), dict):
                out.append(e["args"])
        elif isinstance(e.get("devprof"), dict) and "span" not in e:
            out.append(e["devprof"])
    return out


def print_roofline(entries: List[dict]):
    """The per-compiled-entry roofline table: sampled device/host ms,
    MFU / HBM utilization from the captured XLA costs, and the
    verdict — compute-bound, hbm-bound, host-bound, or unattributed
    (sampled but never cost-captured)."""
    if not entries:
        return

    def fm(v, spec="{:.3f}"):
        return "-" if v is None else spec.format(v)

    name_w = max(len(str(e.get("entry", "?"))) for e in entries)
    name_w = max(name_w, len("Entry"))
    print(f"{'Entry':{name_w}s}  {'Samples':>7s}  {'Dev(ms)':>9s}  "
          f"{'Host(ms)':>9s}  {'MFU':>8s}  {'HBM':>8s}  Verdict")
    for e in sorted(entries, key=lambda e: str(e.get("entry", "?"))):
        print(f"{str(e.get('entry', '?')):{name_w}s}  "
              f"{e.get('samples', 0):7d}  "
              f"{fm(e.get('device_ms_mean')):>9s}  "
              f"{fm(e.get('host_ms_mean')):>9s}  "
              f"{fm(e.get('mfu'), '{:.2%}'):>8s}  "
              f"{fm(e.get('hbm_util'), '{:.2%}'):>8s}  "
              f"{e.get('verdict', '?')}")


def print_blame(per: Dict[int, dict], path: str,
                devprof: List[dict] = ()) -> int:
    if not per:
        print(f"{path}: no per-request serving spans "
              "(need tracing chrome-trace X events with args.request, "
              "or export_spans_jsonl lines)")
        return 1
    rows = list(per.values())
    e2es = [r["e2e_ms"] for r in rows]
    p95 = _pctl(e2es, 95)
    tail = [r for r in rows if r["e2e_ms"] >= p95]
    names = sorted({n for r in rows for n in r["components"]})
    total_e2e = sum(e2es)
    name_w = max([12] + [len(n) for n in names])
    print(f"{len(rows)} requests, E2E p95 {p95:.3f} ms")
    print(f"{'Component':{name_w}s}  {'Total(ms)':>12s}  {'Share':>7s}  "
          f"{'p95(ms)':>10s}  {'TailMean(ms)':>12s}")
    tail_means = {}
    for name in names:
        vals = [r["components"].get(name, 0.0) for r in rows]
        tot = sum(vals)
        tmean = sum(r["components"].get(name, 0.0)
                    for r in tail) / len(tail)
        tail_means[name] = tmean
        share = tot / total_e2e if total_e2e else 0.0
        print(f"{name:{name_w}s}  {tot:12.3f}  {share:7.1%}  "
              f"{_pctl(vals, 95):10.3f}  {tmean:12.3f}")
    dominant = max(names, key=lambda n: tail_means[n])
    print(f"tail blame: {dominant} dominates the E2E p95 tail")
    print_roofline(list(devprof))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="top-N summary of a chrome trace or JSONL run log")
    ap.add_argument("path", help="chrome-trace JSON or runlog .jsonl")
    ap.add_argument("--top", type=int, default=15,
                    help="rows to print (default 15)")
    ap.add_argument("--sort", choices=("total", "calls", "ave"),
                    default="total", help="sort key (default total ms)")
    ap.add_argument("--blame", action="store_true",
                    help="per-request latency-component blame table "
                         "(serving tracing exports only)")
    args = ap.parse_args(argv)

    fmt, events = load_events(args.path)
    if args.blame:
        return print_blame(collect_blame(fmt, events), args.path,
                           collect_devprof(fmt, events))
    rows = (summarize_chrome(events) if fmt == "chrome"
            else summarize_runlog(events))
    if not rows:
        print(f"{args.path}: no events")
        return 0
    key = {"total": "total_ms", "ave": "avg_ms", "calls": "calls"}[args.sort]
    rows.sort(key=lambda a: -a[key])
    rows = rows[:args.top]

    name_w = max(len(r["name"]) for r in rows)
    span_h = "Span(ms)" if fmt == "runlog" else "Total(ms)"
    print(f"{'Event':{name_w}s}  {'Calls':>7s}  {span_h:>10s}  "
          f"{'Avg(ms)':>10s}")
    for r in rows:
        line = (f"{r['name']:{name_w}s}  {r['calls']:7d}  "
                f"{r['total_ms']:10.3f}  {r['avg_ms']:10.3f}")
        means = r.get("means")
        if means:
            extras = ", ".join(f"{k}={v:.4g}" for k, v in means.items())
            line += f"  [{extras}]"
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
